"""Query-plan compiler: QueryBuilder trees → fused top-k kernel plans.

The serving-path replacement for the dense (scores, mask) execution model
(ref: the reference compiles QueryBuilder → Lucene Weight/BulkScorer,
search/internal/ContextIndexSearcher.java:196-232; here the analogous
compilation target is ops/plan.py's sorted segmented-reduction kernel).

A query is *plannable* when it decomposes into:
- postings **groups** — clauses scored/filtered from a text/keyword field's
  postings (match, multi_match, term, terms, constant_score over those),
  each with its own presence requirement (operator=and /
  minimum_should_match inside the clause);
- **dense factors** — pure column predicates (range, exists, ids,
  numeric/date/bool term(s), match_all) whose masks are vectorized
  compares with no scatter anywhere;
composed by at most one level of bool occur semantics (must / filter /
should / must_not + minimum_should_match), or a top-level dis_max /
multi_match over plannable children.

Everything else (scripts, nested bools, positional queries, aggs paths)
falls back to the dense executor — kept for when a full [ND] score vector
is semantically required.

Compilation happens once per shard (terms analyzed, idf from shard-level
stats — exactly the stats the dense path uses); binding resolves term →
postings-block ids per segment.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.mapper import (
    ConstantKeywordFieldType,
    KeywordFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops import bm25 as bm25_ops
from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.ops.device import block_bucket
from elasticsearch_tpu.search import queries as q

NAN = float("nan")
_NEVER = 1 << 30  # requirement no group can meet (pad groups)

# Floor for the selected-block bucket (powers of two above it). Serving
# deployments raise it to collapse the distinct compiled shapes — each
# (bucket, k) pair is one XLA compile (~20-40s on TPU first time).
MIN_PLAN_BUCKET = 0

# Filter/must_not groups at least this many postings blocks wide execute
# as cached dense masks (ops/device.py filter_mask — the LRUQueryCache
# analogue) instead of entering the per-query sort. Smaller filters are
# cheaper to sort than to cache.
FILTER_CACHE_MIN_BLOCKS = 8

# Block-max window pruning (ref: Lucene block-max WAND,
# TopDocsCollectorContext.java:210-217). The docid space splits into
# PRUNE_WINDOWS windows; a window whose BM25 upper bound (from
# block_max_tf / block_min_len) cannot reach the k-th best CPU-verified
# candidate score is dropped, and postings blocks overlapping only
# dropped windows leave the selection before the power-of-two bucket is
# chosen — the sort shrinks, recall stays exactly 1.0. Only queries with
# at least PRUNE_MIN_BLOCKS selected blocks pay the host-side bound pass.
PRUNE_WINDOWS = 512
PRUNE_MIN_BLOCKS = 384


@dataclass
class TermEntry:
    field: str
    term: str
    sub: int          # subgroup id within the group
    weight: float     # idf · boost (0 for pure-presence entries)
    const: bool       # constant-per-match contribution (keyword scoring)


@dataclass
class GroupPlan:
    kind: int                     # plan_ops.MUST / SHOULD / FILTER / MUST_NOT
    req: int                      # distinct subgroups required for presence
    const_score: float            # NaN = sum of contributions
    terms: List[TermEntry] = dc_field(default_factory=list)


@dataclass
class LogicalPlan:
    groups: List[GroupPlan]
    dense: List[Tuple[Any, bool]]         # (QueryBuilder, negate)
    n_must: int                           # postings MUST groups
    n_filter: int                         # postings FILTER groups
    msm: int
    bonus: float                          # constant score of dense must/
                                          # constant clauses every hit gets
    combine: str = "sum"
    tie: float = 0.0
    # expression-tier script_score transform: (source, sorted-params
    # tuple). Applied to the combined per-doc score inside the kernel —
    # BASELINE config 3 rides the batched plan path (ref:
    # ScriptScoreQuery.java:51,91-109; the reference scores per doc
    # through a Lucene ScoreScript, here the expression compiles to one
    # fused columnar transform)
    script: Optional[Tuple[str, tuple]] = None

    def postings_required(self) -> bool:
        """True iff every passing doc must match ≥1 postings group — the
        kernel can only see docs that appear in the gathered postings."""
        return self.n_must >= 1 or self.n_filter >= 1 or self.msm >= 1


# ---------------------------------------------------------------------------
# clause classification
# ---------------------------------------------------------------------------

def _is_postings_field(mapper, field: str) -> bool:
    ft = mapper.field_type(field)
    if isinstance(ft, ConstantKeywordFieldType):
        return False
    return (ft is None or isinstance(ft, (TextFieldType, KeywordFieldType))
            or getattr(ft, "docvalue_kind", None) == "flattened")


def _is_dense_clause(node, mapper) -> bool:
    """Clauses whose do_execute builds masks from dense columns only —
    no postings scatter anywhere (range/exists/ids/match_all and term(s)
    on numeric/date/bool/constant_keyword/range fields)."""
    if isinstance(node, (q.RangeQuery, q.ExistsQuery, q.IdsQuery,
                         q.MatchAllQuery)):
        return True
    if isinstance(node, (q.TermQuery, q.TermsQuery)):
        return not _is_postings_field(mapper, node.field)
    return False


def _analyze(searcher, field: str, text: str) -> List[str]:
    # the dense executor's analysis, verbatim — one tokenization for both
    # paths (queries._analyze_terms only reads .mapper, which ShardSearcher
    # exposes just like SegmentContext)
    return q._analyze_terms(searcher, field, text)


def _idf(searcher, field: str, term: str) -> float:
    doc_count, _ = searcher.stats.field_stats(field)
    df = searcher.stats.doc_freq(field, term)
    return bm25_ops.idf(df, doc_count) if df > 0 else 0.0


# ---------------------------------------------------------------------------
# per-clause group builders (return None when not plannable)
# ---------------------------------------------------------------------------

def _group_for_match(node: "q.MatchQuery", searcher, kind: int,
                     scale: float) -> Optional[GroupPlan]:
    if not _is_postings_field(searcher.mapper, node.field):
        return None
    terms = _analyze(searcher, node.field, node.query)
    if not terms:
        return None  # matches nothing; dense fallback returns empty fast
    uniq = {t: i for i, t in enumerate(sorted(set(terms)))}
    if node.operator == "and":
        req = len(uniq)
    elif node.minimum_should_match:
        # parsed over the token count (duplicates included), clamped to the
        # distinct-term count; ≤1 means "any term" — exactly the dense
        # path's required/need computation (queries.MatchQuery.do_execute)
        r = q.parse_minimum_should_match(
            node.minimum_should_match, len(terms))
        req = 1 if r <= 1 else min(r, len(uniq))
    else:
        req = 1
    g = GroupPlan(kind, req, NAN)
    for t in terms:  # duplicates kept: they double the contribution, as in
        # the dense path (select_blocks extends per occurrence)
        g.terms.append(TermEntry(node.field, t, uniq[t],
                                 _idf(searcher, node.field, t) * scale,
                                 False))
    return g


def _group_for_term(node: "q.TermQuery", searcher, kind: int,
                    scale: float) -> Optional[GroupPlan]:
    mapper = searcher.mapper
    if not _is_postings_field(mapper, node.field):
        return None
    ft = mapper.field_type(node.field)
    term = str(node.value)
    if isinstance(ft, TextFieldType):
        g = GroupPlan(kind, 1, NAN)
        g.terms.append(TermEntry(node.field, term,
                                 0, _idf(searcher, node.field, term) * scale,
                                 False))
        return g
    # keyword/unmapped/flattened: constant score idf·1/(1+k1), no norms
    # (ref: Lucene keyword fields omit norms; see queries.TermQuery)
    const = _idf(searcher, node.field, term) / (1.0 + searcher.k1) * scale
    g = GroupPlan(kind, 1, const)
    g.terms.append(TermEntry(node.field, term, 0, 0.0, False))
    return g


def _group_for_terms(node: "q.TermsQuery", searcher, kind: int,
                     scale: float) -> Optional[GroupPlan]:
    if not _is_postings_field(searcher.mapper, node.field):
        return None
    g = GroupPlan(kind, 1, 1.0 * scale)   # constant_score(1.0) any-of
    for v in node.values:
        g.terms.append(TermEntry(node.field, str(v), 0, 0.0, False))
    return g


def _group_for_clause(node, searcher, kind: int,
                      scale: float) -> Optional[GroupPlan]:
    scale = scale * getattr(node, "boost", 1.0)
    if isinstance(node, q.MatchQuery):
        return _group_for_match(node, searcher, kind, scale)
    if isinstance(node, q.TermQuery):
        return _group_for_term(node, searcher, kind, scale)
    if isinstance(node, q.TermsQuery):
        return _group_for_terms(node, searcher, kind, scale)
    if isinstance(node, q.ConstantScoreQuery):
        inner = _group_for_clause(node.filter_query, searcher, kind, 1.0)
        if inner is None:
            return None
        inner.kind = kind
        inner.const_score = 1.0 * scale   # score is the boost, not BM25
        for t in inner.terms:
            t.weight = 0.0
        return inner
    return None


# ---------------------------------------------------------------------------
# top-level compilation
# ---------------------------------------------------------------------------

def _plan_script_spec(node: "q.ScriptScoreQuery",
                      searcher) -> Optional[Tuple[str, tuple]]:
    """(source, params) when the script can ride the kernel: the
    EXPRESSION tier only (statement scripts interpret per doc on host),
    scalar params, no min_score, and a dry trace over dummy columns
    succeeds (catches vector functions / unsupported constructs)."""
    from elasticsearch_tpu.search.script import (ScriptContext,
                                                 ScriptException,
                                                 _DocColumn,
                                                 compile_script)
    if node.min_score is not None:
        return None
    if not all(isinstance(v, (int, float, str, bool))
               for v in node.params.values()):
        return None
    try:
        compiled = compile_script(node.source)
    except ScriptException:
        return None
    if not getattr(compiled, "vectorized", False):
        return None

    def dummy_cols(field):
        return _DocColumn(jnp.zeros(2, jnp.float32),
                          jnp.zeros(2, bool))
    try:
        out = compiled(ScriptContext(dummy_cols, dict(node.params),
                                     score=jnp.zeros(2, jnp.float32)))
        jnp.asarray(out, jnp.float32)
    except Exception:       # noqa: BLE001 — anything odd → dense path
        return None
    return (node.source, tuple(sorted(node.params.items())))


def compile_plan(query, searcher,
                 post_filter=None) -> Optional[LogicalPlan]:
    """Compile a rewritten query (+ optional post_filter folded in as a
    filter — valid when no aggregations run) into a LogicalPlan, or None
    when the tree needs the dense executor."""
    script_spec = None
    if isinstance(query, q.ScriptScoreQuery):
        script_spec = _plan_script_spec(query, searcher)
        if script_spec is None:
            return None
        query = query.query
    plan = _compile_tree(query, searcher)
    if plan is None:
        return None
    plan.script = script_spec
    if post_filter is not None:
        g = _group_for_clause(post_filter, searcher, plan_ops.FILTER, 1.0)
        if g is not None:
            g.const_score = NAN
            plan.groups.append(g)
            plan.n_filter += 1
        elif _is_dense_clause(post_filter, searcher.mapper):
            plan.dense.append((post_filter, False))
        else:
            return None
    if not plan.postings_required():
        return None
    # negative boosts would feed negative contributions into the kernel's
    # cumsum/cummax segmented sums (which require x >= 0) — dense fallback
    if plan.bonus < 0:
        return None
    for g in plan.groups:
        if any(t.weight < 0 for t in g.terms):
            return None
        if not math.isnan(g.const_score) and g.const_score < 0:
            return None
    return plan


def _compile_tree(query, searcher) -> Optional[LogicalPlan]:
    boost = getattr(query, "boost", 1.0)
    if isinstance(query, q.BoolQuery):
        return _compile_bool(query, searcher, boost)
    if isinstance(query, q.MultiMatchQuery):
        return _compile_multi_match(query, searcher, boost)
    if isinstance(query, q.DisMaxQuery):
        return _compile_dismax(query, searcher, boost)
    g = _group_for_clause(query, searcher, plan_ops.MUST, 1.0)
    if g is not None:
        # top-level boost is inside the group scale already via
        # _group_for_clause's getattr(node, "boost")
        return LogicalPlan([g], [], 1, 0, 0, 0.0)
    return None


def _compile_bool(node: "q.BoolQuery", searcher,
                  boost: float) -> Optional[LogicalPlan]:
    groups: List[GroupPlan] = []
    dense: List[Tuple[Any, bool]] = []
    bonus = 0.0
    n_must = n_filter = 0
    n_required_any = 0  # must+filter clauses of any kind (for msm default)

    for clause in node.must:
        g = _group_for_clause(clause, searcher, plan_ops.MUST, boost)
        if g is not None:
            groups.append(g)
            n_must += 1
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, False))
            # a required constant-score clause adds its score to every hit
            # (dense masks score 1.0·boost in the dense path)
            bonus += getattr(clause, "boost", 1.0) * boost
        else:
            return None
        n_required_any += 1
    for clause in node.filter:
        g = _group_for_clause(clause, searcher, plan_ops.FILTER, 1.0)
        if g is not None:
            g.const_score = NAN   # filters never score
            groups.append(g)
            n_filter += 1
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, False))
        else:
            return None
        n_required_any += 1
    for clause in node.must_not:
        g = _group_for_clause(clause, searcher, plan_ops.MUST_NOT, 1.0)
        if g is not None:
            g.const_score = NAN
            groups.append(g)
        elif _is_dense_clause(clause, searcher.mapper):
            dense.append((clause, True))
        else:
            return None
    for clause in node.should:
        g = _group_for_clause(clause, searcher, plan_ops.SHOULD, boost)
        if g is None:
            return None   # dense should-clauses: conditional +1 scoring —
            # rare; dense fallback keeps exact semantics
        groups.append(g)

    if node.minimum_should_match is None:
        msm = 1 if (node.should and n_required_any == 0) else 0
    else:
        msm = q.parse_minimum_should_match(
            node.minimum_should_match, len(node.should))
    if node.should and msm > len(node.should):
        msm = len(node.should)
    return LogicalPlan(groups, dense, n_must, n_filter, msm, bonus)


def _compile_multi_match(node: "q.MultiMatchQuery", searcher,
                         boost: float) -> Optional[LogicalPlan]:
    fields = node.fields
    if not fields or fields == ["*"]:
        fields = [name for name, ft in searcher.mapper.mapper.fields.items()
                  if isinstance(ft, TextFieldType)]
    if not fields:
        return None
    groups = []
    for f in fields:
        g = _group_for_match(q.MatchQuery(f, node.query), searcher,
                             plan_ops.SHOULD, boost)
        if g is None:
            return None
        groups.append(g)
    if node.type == "most_fields":
        return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="sum")
    if node.type == "best_fields":
        return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="dismax",
                           tie=node.tie_breaker)
    return None   # cross_fields/phrase types: dense fallback


def _compile_dismax(node: "q.DisMaxQuery", searcher,
                    boost: float) -> Optional[LogicalPlan]:
    groups = []
    for sub in node.queries:
        g = _group_for_clause(sub, searcher, plan_ops.SHOULD, boost)
        if g is None:
            return None
        groups.append(g)
    if not groups:
        return None
    return LogicalPlan(groups, [], 0, 0, 1, 0.0, combine="dismax",
                       tie=node.tie_breaker)


# ---------------------------------------------------------------------------
# per-segment binding + execution
# ---------------------------------------------------------------------------

@dataclass
class BoundPlan:
    """A LogicalPlan bound to one segment's device arrays: ready-to-launch
    kernel arguments (the per-query bytes shipped to device are just the
    selection arrays — a few hundred bytes)."""
    streams: List[plan_ops.FieldStream]
    group_kind: np.ndarray
    group_req: np.ndarray
    group_const: np.ndarray
    dense_mask: Optional[jnp.ndarray]
    n_must: int
    n_filter: int
    msm: int
    bonus: float
    tie: float
    combine: str
    empty: bool = False   # no query term exists in this segment
    # host copies of cached-filter masks folded into dense_mask, as
    # (mask, negate) — lets block-max pruning validate its threshold
    # candidates CPU-side (no readback)
    host_masks: List[Tuple[np.ndarray, bool]] = dc_field(default_factory=list)
    # True when block-max pruning dropped blocks: the kernel's matching-doc
    # count is then a LOWER bound (hits.total relation becomes "gte")
    pruned: bool = False
    # dense_mask is a CACHED shared object (composed filter column):
    # batch cohorts may key on its identity and pass it unbatched
    dense_shared: bool = False
    # stable per-(segment, script) closure applied to the per-doc score
    # inside the kernel (ops/plan.plan_topk_body script_fn); identity is
    # the batch-cohort key, so it must come from _bind_script's cache
    script_fn: Optional[Any] = None


def _group_field_blocks(g: GroupPlan, ctx) -> Optional[Tuple[str, int]]:
    """(field, total postings blocks) of a single-field group, else None."""
    fields = {t.field for t in g.terms}
    if len(fields) != 1:
        return None
    fname = next(iter(fields))
    dp = ctx.device.postings.get(fname)
    if dp is None:
        return fname, 0
    n = 0
    for t in g.terms:
        tid = dp.host.term_id(t.term)
        if tid >= 0:
            n += int(dp.term_block_count[tid])
    return fname, n


def _convert_filters(plan: LogicalPlan, ctx):
    """Split groups into kernel groups vs cached-mask conversions.

    FILTER / MUST_NOT groups with any-of presence semantics (req <= 1)
    and ≥ FILTER_CACHE_MIN_BLOCKS postings blocks execute as dense cached
    masks (ops/device.py filter_mask — ref: Lucene LRUQueryCache via
    UsageTrackingQueryCachingPolicy: hot filters become bitsets), so their
    postings never enter the query's sort. At least one enumerating
    postings group must remain — the kernel only sees docs present in the
    gathered postings.

    Returns (kernel_groups, [(field, terms, negate)], kernel_filter_count).
    """
    must_enum = plan.n_must >= 1
    should_enum = plan.msm >= 1 and any(
        g.kind == plan_ops.SHOULD for g in plan.groups)

    sized = []
    for gi, g in enumerate(plan.groups):
        if g.kind not in (plan_ops.FILTER, plan_ops.MUST_NOT) or g.req > 1:
            continue
        fb = _group_field_blocks(g, ctx)
        if fb is not None and fb[1] >= FILTER_CACHE_MIN_BLOCKS:
            sized.append((fb[1], gi, g, fb[0]))
    sized.sort(key=lambda e: -e[0])   # biggest filters convert first

    n_filters_left = plan.n_filter
    converted: List[Tuple[str, List[str], bool]] = []
    convert_ids = set()
    for _, gi, g, fname in sized:
        if g.kind == plan_ops.MUST_NOT:
            convert_ids.add(gi)
            converted.append((fname, [t.term for t in g.terms], True))
        elif must_enum or should_enum or n_filters_left > 1:
            convert_ids.add(gi)
            converted.append((fname, [t.term for t in g.terms], False))
            n_filters_left -= 1
    kernel = [g for gi, g in enumerate(plan.groups) if gi not in convert_ids]
    return kernel, converted, n_filters_left


def bind_plan(plan: LogicalPlan, ctx, k: int = 10,
              allow_prune: bool = False) -> BoundPlan:
    """Resolve terms → block ids against one segment (ctx: SegmentContext).
    Selection arrays bucket to powers of two so NB takes O(log) distinct
    values across queries (XLA compile-cache discipline, ops/device.py).

    ``allow_prune=True`` (legal when the caller treats hits.total as a
    lower bound — track_total_hits thresholds) additionally applies
    block-max window pruning (_prune_fields): docid windows whose BM25
    upper bound cannot reach a CPU-validated top-k threshold drop out of
    the selection entirely, shrinking the sorted bucket (ref: Lucene
    block-max WAND, TopDocsCollectorContext.java:210-217)."""
    kernel_groups, converted, n_filter = _convert_filters(plan, ctx)
    ngroups = len(kernel_groups)
    by_field: Dict[str, List[Tuple[int, int, float, bool, str]]] = {}
    for gi, g in enumerate(kernel_groups):
        for t in g.terms:
            by_field.setdefault(t.field, []).append(
                (gi, t.sub, t.weight, t.const, t.term))

    # cached dense masks first — their HOST copies also validate the
    # pruning threshold below. The COMPOSED mask of the whole filter set
    # is itself cached so repeated filter combos share one device object
    # (batch cohorts key on its identity).
    dense_mask = None
    dense_shared = False
    host_masks: List[Tuple[np.ndarray, bool]] = []
    if converted:
        dense_mask, comp_host = ctx.device.composed_filter_mask(converted)
        dense_shared = True
        host_masks.append((comp_host, False))
    for clause, negate in plan.dense:
        _, m = clause.do_execute(ctx)
        m = (~m) if negate else m
        dense_mask = m if dense_mask is None else (dense_mask & m)
        dense_shared = False   # device-column factors: identity not cached

    # ---- unpadded per-field selections (kept separate so pruning can
    # drop blocks before the power-of-two bucket is chosen)
    fields: List[Tuple[str, Any, np.ndarray, np.ndarray, np.ndarray,
                       np.ndarray, np.ndarray]] = []
    for fname, entries in by_field.items():
        dp = ctx.device.postings.get(fname)
        if dp is None:
            continue
        starts: List[int] = []
        counts: List[int] = []
        egrp: List[int] = []
        esub: List[int] = []
        ew: List[float] = []
        econst: List[bool] = []
        for gi, sub, w, const, term in entries:
            tid = dp.host.term_id(term)
            if tid < 0:
                continue
            starts.append(int(dp.term_block_start[tid]))
            counts.append(int(dp.term_block_count[tid]))
            egrp.append(gi)
            esub.append(sub)
            ew.append(w)
            econst.append(const)
        if not starts:
            continue
        # vectorized range expansion (per-request host path: no Python
        # per-block loops)
        counts_np = np.asarray(counts, np.int64)
        tot = int(counts_np.sum())
        if tot == 0:
            continue
        rep = np.repeat(np.arange(len(starts)), counts_np)
        offs = (np.arange(tot, dtype=np.int64)
                - np.repeat(np.cumsum(counts_np) - counts_np, counts_np))
        sel = (np.asarray(starts, np.int64)[rep] + offs).astype(np.int32)
        fields.append((fname, dp,
                       sel,
                       np.asarray(egrp, np.int32)[rep],
                       np.asarray(esub, np.int32)[rep],
                       np.asarray(ew, np.float32)[rep],
                       np.asarray(econst, bool)[rep],
                       rep.astype(np.int32)))

    pruned = False
    if allow_prune and fields:
        fields, pruned = _prune_fields(plan, kernel_groups, fields, ctx, k,
                                       host_masks)

    streams: List[plan_ops.FieldStream] = []
    any_entries = False
    for fname, dp, sel_u, grp_u, sub_u, w_u, c_u, _ent in fields:
        tot = len(sel_u)
        if tot == 0:
            continue
        any_entries = True
        n = max(block_bucket(tot), MIN_PLAN_BUCKET)
        sel = np.full(n, dp.zero_block, np.int32)
        sel[:tot] = sel_u
        grp = np.full(n, ngroups, np.int32)   # pads: clipped; tf=0 ⇒ inert
        grp[:tot] = grp_u
        sub_a = np.zeros(n, np.int32)
        sub_a[:tot] = sub_u
        w_a = np.zeros(n, np.float32)
        w_a[:tot] = w_u
        c_a = np.zeros(n, bool)
        c_a[:tot] = c_u
        # selections stay NUMPY: the jit boundary uploads them
        # asynchronously per launch, while batching stacks them with a
        # microseconds host np.stack — stacking device arrays instead
        # costs ~10ms of GIL-held dispatch per launch (measured), which
        # serializes the whole concurrent serving path
        streams.append(plan_ops.FieldStream(
            dp.block_docids, dp.block_tfs, dp.doc_lens,
            jnp.float32(ctx.stats.field_stats(fname)[1]),
            sel, grp, sub_a, w_a, c_a))

    gpad = max(4, block_bucket(max(1, ngroups)) if ngroups else 4)
    kind = np.full(gpad, plan_ops.FILTER, np.int32)
    req = np.full(gpad, _NEVER, np.int32)
    const = np.full(gpad, NAN, np.float32)
    for gi, g in enumerate(kernel_groups):
        kind[gi] = g.kind
        req[gi] = g.req
        const[gi] = g.const_score
    # pad groups: FILTER with unreachable req — never present, and absent
    # FILTER groups don't block (n_filter counts only real groups)

    return BoundPlan(streams, kind, req, const, dense_mask,
                     plan.n_must, n_filter, plan.msm, plan.bonus,
                     plan.tie, plan.combine, empty=not any_entries,
                     host_masks=host_masks, pruned=pruned,
                     dense_shared=dense_shared,
                     script_fn=(_bind_script(ctx, plan.script)
                                if plan.script is not None else None))


# ---------------------------------------------------------------------------
# block-max window pruning (host-side bound pass; ref: Lucene block-max
# WAND / MaxScore — TopDocsCollectorContext.java:210-217)
# ---------------------------------------------------------------------------

def _block_bounds(dp):
    """Per-block (first, last) docids, cached on the DevicePostings.
    Valid postings are a docid-ascending prefix of each block (tf=0 pads
    sit at the end with docid 0), so the masked max is the last docid."""
    lo = getattr(dp, "_block_lo", None)
    if lo is None:
        pf = dp.host
        dp._block_lo = pf.block_docids[:, 0].astype(np.int64)
        dp._block_hi = np.where(pf.block_tfs > 0.0, pf.block_docids,
                                0).max(axis=1).astype(np.int64)
        lo = dp._block_lo
    return lo, dp._block_hi




def _prune_fields(plan: LogicalPlan, kernel_groups: List[GroupPlan],
                  fields, ctx, k: int,
                  host_masks: List[Tuple[np.ndarray, bool]]):
    """Drop postings blocks that provably cannot affect the top-k.

    Correctness argument (recall exactly 1.0):
    - θ is the k-th largest *single-entry* contribution among ≥k distinct
      docs that verifiably PASS the whole query (live + every filter,
      validated host-side) — each doc's true score is ≥ its partial
      contribution, so the true k-th best score is ≥ θ.
    - A docid window's bound sums per-term maxima of
      w·max_tf/(max_tf + k1·(1−b+b·min_len/avg)) — an upper bound on any
      doc's score inside the window (score is monotonic ↑tf, ↓len).
    - Windows with bound < θ therefore contain no top-k member; blocks
      overlapping only such windows drop from every group (scoring,
      filter, must_not alike), so surviving docs keep ALL their postings
      and score exactly.
    The kernel's matching-doc count becomes a lower bound (`pruned=True`
    → hits.total relation "gte"), which is why callers gate this on
    track_total_hits thresholds.
    """
    total_blocks = sum(len(f[2]) for f in fields)
    if total_blocks < PRUNE_MIN_BLOCKS or plan.dense or plan.bonus < 0:
        return fields, False

    # adaptive backoff: on corpora whose docid space shows no block-max
    # skew (uniform synthetic data, shuffled ingestion) the bound pass
    # never prunes — exponentially skip attempts per segment so the host
    # cost vanishes there (the spirit of Lucene's usage-tracking policy)
    dev = ctx.device
    skip = getattr(dev, "_prune_skip", 0)
    if skip > 0:
        dev._prune_skip = skip - 1
        return fields, False

    # ---- eligibility + candidate sources + host-validated filters
    must_ids = [gi for gi, g in enumerate(kernel_groups)
                if g.kind == plan_ops.MUST]
    cand_ids = set()
    small_filters: List[Tuple[int, bool]] = []   # (group id, negate)
    for gi, g in enumerate(kernel_groups):
        if g.kind == plan_ops.MUST:
            if len(must_ids) != 1 or plan.msm >= 1 or g.req > 1:
                return fields, False
            cand_ids.add(gi)
        elif g.kind == plan_ops.SHOULD:
            if not must_ids and plan.msm <= 1 and g.req <= 1:
                cand_ids.add(gi)
        elif g.kind == plan_ops.MUST_NOT:
            # a kernel must_not whose postings prune away would let the
            # matching-doc count OVERcount (excluded docs sneaking back
            # in) — converted must_nots are dense columns and stay exact
            return fields, False
        else:   # small FILTER staying in the kernel
            if g.req > 1 or len({t.field for t in g.terms}) != 1:
                return fields, False
            small_filters.append((gi, False))
    if must_ids:
        cand_ids = set(must_ids)
    if not cand_ids:
        return fields, False

    nd = ctx.segment.n_docs
    if nd <= 0:
        return fields, False
    wsz = max(1, -(-nd // PRUNE_WINDOWS))
    W = -(-nd // wsz)
    k1, b = ctx.k1, ctx.b
    ng = len(kernel_groups)
    gconst = np.asarray([g.const_score for g in kernel_groups], np.float32)
    gkind = np.asarray([g.kind for g in kernel_groups], np.int32)

    # validation mask over real docs: live + converted cached filters +
    # small kernel filters
    vmask = np.asarray(ctx.segment.live[:nd], bool).copy()
    for hm, negate in host_masks:
        vmask &= ~hm[:nd] if negate else hm[:nd]
    for gi, negate in small_filters:
        g = kernel_groups[gi]
        fname = g.terms[0].field
        dp = ctx.device.postings.get(fname)
        if dp is None:
            m = np.zeros(nd, bool)
        else:
            from elasticsearch_tpu.ops.device import host_any_mask
            m = host_any_mask(dp.host, [t.term for t in g.terms], nd)
        vmask &= ~m if negate else m

    # ---- per-(group, window) upper bounds + θ candidates
    group_wb = np.zeros((ng, W), np.float64)
    group_any = np.zeros((ng, W), bool)     # presence for const groups
    theta = -np.inf
    probe_j = -(-k // 128) + 4              # blocks per candidate entry
    per_field = []                          # (wlo, whi) kept for drop pass
    for fname, dp, sel_u, grp_u, sub_u, w_u, c_u, ent_u in fields:
        pf = dp.host
        avg = ctx.stats.field_stats(fname)[1]
        lo_all, hi_all = _block_bounds(dp)
        wlo = (lo_all[sel_u] // wsz).astype(np.int64)
        whi = np.maximum(hi_all[sel_u] // wsz, wlo).astype(np.int64)
        per_field.append((wlo, whi))
        mtf = pf.block_max_tf[sel_u].astype(np.float64)
        mln = pf.block_min_len[sel_u].astype(np.float64)
        norm = k1 * (1.0 - b + b * mln / avg)
        sat = np.where(mtf > 0.0, mtf / (mtf + norm), 0.0)
        is_sum_grp = np.isnan(gconst[grp_u])   # NaN const ⇒ sum-of-contribs
        ub = np.where(is_sum_grp,
                      np.where(c_u, w_u, w_u * sat),
                      (mtf > 0.0).astype(np.float64))

        # per-entry window maxima (entries are windows-disjoint block runs)
        n_ent = int(ent_u[-1]) + 1 if len(ent_u) else 0
        if n_ent > 64:
            # pathological entry counts (huge terms lists in the kernel)
            # would make the per-entry bound pass itself the bottleneck
            return fields, False
        lens = whi - wlo + 1
        tot = int(lens.sum())
        csum = np.cumsum(lens) - lens
        widx = (np.repeat(wlo, lens)
                + (np.arange(tot, dtype=np.int64) - np.repeat(csum, lens)))
        eidx = np.repeat(ent_u.astype(np.int64), lens)
        ewm = np.zeros(n_ent * W, np.float64)
        np.maximum.at(ewm, eidx * W + widx, np.repeat(ub, lens))
        ewm = ewm.reshape(n_ent, W)

        # fold entries into group bounds: NaN-const groups SUM their
        # entries' maxima (duplicate query terms double-count, matching
        # the kernel); const groups need presence only
        ent_first = np.flatnonzero(np.diff(ent_u, prepend=-1))
        for e0 in ent_first:
            e = int(ent_u[e0])
            gi = int(grp_u[e0])
            if np.isnan(gconst[gi]):
                group_wb[gi] += ewm[e]
            group_any[gi] |= ewm[e] > 0.0

            # θ probe: top-J blocks of candidate entries, exact partial
            # contributions validated against vmask
            if gi not in cand_ids:
                continue
            blocks = sel_u[ent_u == e]
            ub_e = ub[ent_u == e]
            j = min(probe_j, len(blocks))
            topb = blocks[np.argpartition(ub_e, len(ub_e) - j)[len(ub_e) - j:]] \
                if j < len(blocks) else blocks
            d = pf.block_docids[topb].reshape(-1)
            tf = pf.block_tfs[topb].reshape(-1).astype(np.float64)
            ok = (tf > 0.0) & (d < nd)
            d, tf = d[ok], tf[ok]
            ok = vmask[d]
            d, tf = d[ok], tf[ok]
            if len(d) < k:
                continue
            if not np.isnan(gconst[gi]):
                cand = np.full(len(d), float(gconst[gi]))
            elif bool(c_u[e0]):
                cand = np.full(len(d), float(w_u[e0]))
            else:
                dnorm = k1 * (1.0 - b
                              + b * pf.field_lengths[d].astype(np.float64)
                              / avg)
                cand = float(w_u[e0]) * tf / (tf + dnorm)
            th = np.partition(cand, len(cand) - k)[len(cand) - k]
            if th > theta:
                theta = th

    def _fail():
        fails = getattr(dev, "_prune_fail", 0) + 1
        dev._prune_fail = fails
        dev._prune_skip = min(256, 2 ** min(fails, 8))
        return fields, False

    if not np.isfinite(theta) or theta <= 0.0:
        return _fail()

    # ---- combine group bounds → per-window score bound
    scoring = (gkind == plan_ops.MUST) | (gkind == plan_ops.SHOULD)
    gb = np.where(np.isnan(gconst)[:, None], group_wb,
                  np.nan_to_num(gconst)[:, None] * group_any)
    gb = gb[scoring]
    if plan.combine == "dismax":
        mx = gb.max(axis=0) if len(gb) else np.zeros(W)
        wb = mx + plan.tie * (gb.sum(axis=0) - mx)
    else:
        wb = gb.sum(axis=0) if len(gb) else np.zeros(W)

    # float32 kernel sums can exceed the float64 bound by rounding —
    # keep a small safety margin
    keep_w = wb >= theta * (1.0 - 1e-5)
    if keep_w.all():
        return _fail()
    ck = np.concatenate([[0], np.cumsum(keep_w)])

    out = []
    pruned = False
    for (fname, dp, sel_u, grp_u, sub_u, w_u, c_u, ent_u), (wlo, whi) in zip(
            fields, per_field):
        blk_keep = (ck[np.minimum(whi, W - 1) + 1] - ck[wlo]) > 0
        if blk_keep.all():
            out.append((fname, dp, sel_u, grp_u, sub_u, w_u, c_u, ent_u))
            continue
        pruned = True
        out.append((fname, dp, sel_u[blk_keep], grp_u[blk_keep],
                    sub_u[blk_keep], w_u[blk_keep], c_u[blk_keep],
                    ent_u[blk_keep]))
    if pruned:
        dev._prune_fail = 0
    else:
        fails = getattr(dev, "_prune_fail", 0) + 1
        dev._prune_fail = fails
        dev._prune_skip = min(256, 2 ** min(fails, 8))
    return out, pruned


def _bind_script(ctx, script_spec):
    """Per-(DeviceSegment, script) closure over the segment's device
    numeric columns — CACHED on the DeviceSegment so its identity is
    stable (the kernel jits on it as a static argument, and batch
    cohorts key on it)."""
    from elasticsearch_tpu.search.script import (ScriptContext,
                                                 ScriptException,
                                                 _DocColumn,
                                                 compile_script)
    dev = ctx.device
    cache = getattr(dev, "_plan_scripts", None)
    if cache is None:
        cache = dev._plan_scripts = {}
    fn = cache.get(script_spec)
    if fn is None:
        compiled = compile_script(script_spec[0])
        params = dict(script_spec[1])
        numerics = dev.numerics
        missing = dev.numeric_missing

        def fn(score, ids):
            def doc_columns(field):
                col = numerics.get(field)
                if col is None:
                    raise ScriptException(
                        f"unknown numeric field [{field}]")
                return _DocColumn(jnp.take(col, ids),
                                  jnp.take(missing[field], ids))
            sctx = ScriptContext(doc_columns, params, score=score)
            return jnp.asarray(compiled(sctx), jnp.float32)
        cache[script_spec] = fn
    return fn


def execute_bound(bp: BoundPlan, ctx, k: int, k1: float, b: float,
                  after_score: Optional[float] = None):
    """Launch the fused kernel for one segment → host (vals[k], ids[k],
    total). The device result is PACKED into one buffer so the whole
    query costs exactly one device→host readback (ops/plan.pack_result —
    a 3× latency lever under the axon tunnel's degraded-readback mode)."""
    if bp.empty:
        return (np.full(k, -np.inf, np.float32),
                np.full(k, plan_ops._SENTINEL, np.int32), 0)
    packed = plan_ops.plan_topk(
        bp.streams, bp.group_kind, bp.group_req, bp.group_const,
        ctx.live, bp.dense_mask, bp.n_must, bp.n_filter, bp.msm,
        bonus=bp.bonus, tie=bp.tie, k1=k1, b=b, k=k, combine=bp.combine,
        after_score=after_score, packed=True, script_fn=bp.script_fn)
    return plan_ops.unpack_result(np.asarray(packed), k)
