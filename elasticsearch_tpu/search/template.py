"""Search templates: a mustache engine + render pipeline.

ref: modules/lang-mustache — `_search/template`, `_render/template`,
`_msearch/template`; the template source is a (JSON) string rendered with
mustache then parsed. Supported surface: ``{{var}}`` (JSON-string-escaped),
``{{{var}}}`` (raw), ``{{#toJson}}var{{/toJson}}``, sections
``{{#x}}…{{/x}}`` (truthy / list iteration), inverted ``{{^x}}…{{/x}}``
(the "default value" idiom), ``{{.}}`` inside list sections,
``{{#join}}var{{/join}}``, comments ``{{! …}}``, and dotted paths.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ParsingException

_TAG = re.compile(r"{{\s*([#^/!{&]?)\s*([^}]*?)\s*}?}}")


def _lookup(path: str, stack: List[Any]) -> Any:
    if path == ".":
        return stack[-1]
    for frame in reversed(stack):
        cur = frame
        found = True
        for part in path.split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                found = False
                break
        if found:
            return cur
    return None


def _escape_json_string(value: Any) -> str:
    """Render a scalar for splicing inside a JSON template string."""
    if value is None:
        return ""
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return json.dumps(value)
    if isinstance(value, (dict, list)):
        return json.dumps(value)
    return json.dumps(str(value))[1:-1]  # escaped, without the quotes


def _parse(template: str) -> List[Tuple[str, Any]]:
    """Tokenize into [('text', s) | ('var', name, raw) | ('section',
    name, inverted, subtokens)]."""
    tokens: List[Tuple[str, Any]] = []
    stack = [tokens]
    pos = 0
    for m in _TAG.finditer(template):
        if m.start() > pos:
            stack[-1].append(("text", template[pos:m.start()]))
        sigil, name = m.group(1), m.group(2).strip()
        if sigil == "!":
            pass  # comment
        elif sigil in ("#",):
            sub: List[Tuple[str, Any]] = []
            stack[-1].append(("section", name, False, sub))
            stack.append(sub)
        elif sigil == "^":
            sub = []
            stack[-1].append(("section", name, True, sub))
            stack.append(sub)
        elif sigil == "/":
            if len(stack) == 1:
                raise ParsingException(
                    f"unbalanced section close [{name}] in template")
            stack.pop()
        elif sigil in ("{", "&"):
            stack[-1].append(("var", name, True))
        else:
            stack[-1].append(("var", name, False))
        pos = m.end()
    if pos < len(template):
        stack[-1].append(("text", template[pos:]))
    if len(stack) != 1:
        raise ParsingException("unclosed section in template")
    return tokens


def _render(tokens: List[Tuple[str, Any]], stack: List[Any]) -> str:
    out: List[str] = []
    for tok in tokens:
        kind = tok[0]
        if kind == "text":
            out.append(tok[1])
        elif kind == "var":
            _, name, raw = tok
            v = _lookup(name, stack)
            if v is None:
                continue
            if raw:
                out.append(json.dumps(v) if isinstance(v, (dict, list))
                           else str(v))
            else:
                out.append(_escape_json_string(v))
        else:  # section
            _, name, inverted, sub = tok
            if name == "toJson":
                # {{#toJson}}var{{/toJson}} — splice the param as JSON
                inner = _render(sub, stack).strip()
                out.append(json.dumps(_lookup(inner, stack)))
                continue
            if name == "join":
                inner = _render(sub, stack).strip()
                v = _lookup(inner, stack) or []
                out.append(",".join(str(x) for x in v))
                continue
            v = _lookup(name, stack)
            # mustache falsiness: null/missing, false, empty list — NOT 0
            # or empty string (ref: mustache spec; the ES default-value
            # idiom must work for size=0)
            truthy = not (v is None or v is False or v == [])
            if inverted:
                if not truthy:
                    out.append(_render(sub, stack))
            elif isinstance(v, list):
                for item in v:
                    out.append(_render(sub, stack + [item]))
            elif truthy:
                frame = v if isinstance(v, dict) else v
                out.append(_render(sub, stack + [frame]))
    return "".join(out)


def render_template(source: Any, params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Render a template (string or object) with params into the search
    body (ref: TransportSearchTemplateAction → MustacheScriptEngine)."""
    params = params or {}
    text = source if isinstance(source, str) else json.dumps(source)
    rendered = _render(_parse(text), [params])
    try:
        return json.loads(rendered)
    except json.JSONDecodeError as e:
        raise ParsingException(
            f"rendered template is not valid JSON: {e}: {rendered[:200]}")
