"""Positional (phrase) matching over token streams.

The TPU-first split of Lucene's PhraseQuery (ref: Lucene
ExactPhraseMatcher/SloppyPhraseMatcher, consumed via
index/search/MatchQuery.java phrase path): the device does the heavy
filtering — a conjunctive match over the phrase's terms via the postings
block kernels — and position verification runs vectorized on the host over
only the few surviving candidates' token-stream rows. This mirrors the
segment format's block-max design: coarse dense filter first, exact check
on survivors (SURVEY.md §7 "hard parts" #1).

Scoring matches Lucene: the phrase is scored as a pseudo-term with
tf = phrase frequency and weight = sum of the member terms' idfs
(ref: Lucene PhraseWeight — TermStatistics of all terms are summed).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def exact_phrase_freqs(tokens: np.ndarray,      # int32 [C, L] candidate rows
                       term_ids: Sequence[int]  # phrase term ids, len P >= 1
                       ) -> np.ndarray:
    """Phrase occurrence count per candidate row (slop = 0), vectorized:
    an occurrence at position p is ``all_j tokens[:, p+j] == term_ids[j]``."""
    C, L = tokens.shape
    P = len(term_ids)
    if L < P:
        return np.zeros(C, np.int64)
    n_pos = L - P + 1
    match = np.ones((C, n_pos), bool)
    for j, tid in enumerate(term_ids):
        match &= tokens[:, j : j + n_pos] == tid
    return match.sum(axis=1)


def sloppy_phrase_freqs(tokens: np.ndarray, lengths: np.ndarray,
                        term_ids: Sequence[int], slop: int,
                        last_alternatives: Optional[Sequence[int]] = None
                        ) -> np.ndarray:
    """Sloppy phrase frequency per candidate row.

    Greedy alignment: for each occurrence p0 of the first term, each later
    term j must appear at an UNUSED position q with ``|q - j - p0| <= slop``
    (the nearest such q is taken and consumed — repeated terms need
    distinct positions, as in Lucene's SloppyPhraseMatcher). Covers
    in-order and moved-within-slop matches without Lucene's full alignment
    search. ``last_alternatives`` extends the final slot to an any-of set
    (the match_phrase_prefix expansion).
    """
    if slop <= 0 and last_alternatives is None:
        return exact_phrase_freqs(tokens, term_ids)
    C = tokens.shape[0]
    freqs = np.zeros(C, np.int64)
    n_slots = len(term_ids) + (1 if last_alternatives is not None else 0)
    for c in range(C):
        row = tokens[c, : lengths[c]]
        positions: List[np.ndarray] = [np.nonzero(row == tid)[0]
                                       for tid in term_ids]
        if last_alternatives is not None:
            positions.append(np.nonzero(np.isin(row, last_alternatives))[0])
        if any(len(p) == 0 for p in positions):
            continue
        count = 0
        for p0 in positions[0]:
            used = {int(p0)}
            ok = True
            for j in range(1, n_slots):
                target = p0 + j
                best = None
                for q in positions[j]:
                    qi = int(q)
                    if qi in used or abs(qi - target) > slop:
                        continue
                    if best is None or abs(qi - target) < abs(best - target):
                        best = qi
                if best is None:
                    ok = False
                    break
                used.add(best)
            if ok:
                count += 1
        freqs[c] = count
    return freqs


def phrase_prefix_freqs(tokens: np.ndarray,
                        term_ids: Sequence[int],
                        last_term_ids: Sequence[int]) -> np.ndarray:
    """match_phrase_prefix: fixed prefix terms followed by ANY of
    ``last_term_ids`` (the prefix expansions of the final token)."""
    C, L = tokens.shape
    P = len(term_ids) + 1
    if L < P or not last_term_ids:
        return np.zeros(C, np.int64)
    n_pos = L - P + 1
    match = np.ones((C, n_pos), bool)
    for j, tid in enumerate(term_ids):
        match &= tokens[:, j : j + n_pos] == tid
    j = len(term_ids)
    last = np.zeros((C, n_pos), bool)
    window = tokens[:, j : j + n_pos]
    for tid in last_term_ids:
        last |= window == tid
    match &= last
    return match.sum(axis=1)
