"""Parent/child join queries (ref: modules/parent-join —
HasChildQueryBuilder, HasParentQueryBuilder, ParentIdQueryBuilder).

The join is executed shard-locally (parents and children share a shard by
routing, as in the reference): the inner query runs first over the shard's
segments, matched ids are joined through the ``{field}#parent`` keyword
doc values, and the result is rewritten into an id→score lookup query.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import QueryShardException
from elasticsearch_tpu.index.mapper import JoinFieldType
from elasticsearch_tpu.search.queries import QueryBuilder


def _join_field(mapper) -> Optional[JoinFieldType]:
    for ft in mapper.mapper.fields.values():
        if isinstance(ft, JoinFieldType):
            return ft
    return None


def _relation_docs(seg, jf_name: str, relations: List[str]) -> np.ndarray:
    """Bool mask [n_docs] of docs whose join relation is one of
    `relations` (host-side ordinal compare)."""
    kv = seg.keywords.get(jf_name)
    out = np.zeros(seg.n_docs, bool)
    if kv is None:
        return out
    want = {kv.terms.index(r) for r in relations if r in kv.terms}
    if not want:
        return out
    for o in want:
        out |= kv.ords[: seg.n_docs] == o
    return out


class _IdScoreQuery(QueryBuilder):
    """Matches docs whose _id is a key of `scores` (the post-join result
    set); used as the rewrite target of has_child."""

    name = "_id_scores"

    def __init__(self, scores: Dict[str, float]):
        super().__init__()
        self.scores = scores

    def do_execute(self, ctx):
        m = np.zeros(ctx.n_docs_padded, bool)
        s = np.zeros(ctx.n_docs_padded, np.float32)
        for doc_id, score in self.scores.items():
            d = ctx.segment.docid_for(doc_id)
            if d >= 0:
                m[d] = True
                s[d] = score
        return jnp.asarray(s), jnp.asarray(m)


class _ParentRefScoreQuery(QueryBuilder):
    """Matches docs whose ``{field}#parent`` value is a key of `scores`
    and whose relation is in `child_relations`; the rewrite target of
    has_parent."""

    name = "_parent_ref_scores"

    def __init__(self, jf_name: str, child_relations: List[str],
                 scores: Dict[str, float]):
        super().__init__()
        self.jf_name = jf_name
        self.child_relations = child_relations
        self.scores = scores

    def do_execute(self, ctx):
        seg = ctx.segment
        rel_mask = _relation_docs(seg, self.jf_name, self.child_relations)
        kv = seg.keywords.get(f"{self.jf_name}#parent")
        m = np.zeros(ctx.n_docs_padded, bool)
        s = np.zeros(ctx.n_docs_padded, np.float32)
        if kv is not None:
            for d in np.nonzero(rel_mask)[0]:
                for pid in kv.get(int(d)):
                    if pid in self.scores:
                        m[d] = True
                        s[d] = self.scores[pid]
        return jnp.asarray(s), jnp.asarray(m)


def _score_reduce(values: List[float], mode: str) -> float:
    if mode == "none":
        return 1.0
    if mode == "sum":
        return float(sum(values))
    if mode == "avg":
        return float(sum(values) / len(values))
    if mode == "min":
        return float(min(values))
    return float(max(values))  # "max" (default for scoring modes)


class HasChildQuery(QueryBuilder):
    """ref: HasChildQueryBuilder — matches parent docs having matching
    children; score_mode none|max|sum|avg|min; min/max_children bounds."""

    name = "has_child"

    def __init__(self, child_type: str, query: QueryBuilder,
                 score_mode: str = "none", min_children: int = 1,
                 max_children: Optional[int] = None,
                 ignore_unmapped: bool = False):
        super().__init__()
        self.child_type = child_type
        self.query = query
        self.score_mode = score_mode
        self.min_children = max(1, int(min_children))
        self.max_children = max_children
        self.ignore_unmapped = ignore_unmapped

    def rewrite(self, searcher) -> QueryBuilder:
        from elasticsearch_tpu.search.queries import MatchNoneQuery
        if not hasattr(searcher, "_contexts"):
            return self  # coordinator stage; join is shard-local
        jf = _join_field(searcher.mapper)
        if jf is None:
            if self.ignore_unmapped:
                return MatchNoneQuery()
            raise QueryShardException(
                "[has_child] no join field has been configured")
        if jf.parent_of(self.child_type) is None:
            if self.ignore_unmapped:
                return MatchNoneQuery()
            raise QueryShardException(
                f"[has_child] join relation [{self.child_type}] is not a "
                f"child of any parent")
        inner = self.query.rewrite(searcher)
        child_scores: Dict[str, List[float]] = {}
        for ctx in searcher._contexts():
            if ctx.segment.n_docs == 0:
                continue
            scores, mask = inner.execute(ctx)
            rel = _relation_docs(ctx.segment, jf.name, [self.child_type])
            m = np.asarray(mask)[: ctx.segment.n_docs] & rel & \
                ctx.segment.live[: ctx.segment.n_docs]
            sc = np.asarray(scores)
            kv = ctx.segment.keywords.get(f"{jf.name}#parent")
            if kv is None:
                continue
            for d in np.nonzero(m)[0]:
                for pid in kv.get(int(d)):
                    child_scores.setdefault(pid, []).append(float(sc[d]))
        out: Dict[str, float] = {}
        for pid, vals in child_scores.items():
            if len(vals) < self.min_children:
                continue
            if self.max_children is not None and len(vals) > int(self.max_children):
                continue
            out[pid] = _score_reduce(vals, self.score_mode)
        q = _IdScoreQuery(out)
        q.boost = self.boost
        return q


class HasParentQuery(QueryBuilder):
    """ref: HasParentQueryBuilder — matches child docs whose parent
    matches; `score` propagates the parent's score."""

    name = "has_parent"

    def __init__(self, parent_type: str, query: QueryBuilder,
                 score: bool = False, ignore_unmapped: bool = False):
        super().__init__()
        self.parent_type = parent_type
        self.query = query
        self.score = score
        self.ignore_unmapped = ignore_unmapped

    def rewrite(self, searcher) -> QueryBuilder:
        from elasticsearch_tpu.search.queries import MatchNoneQuery
        if not hasattr(searcher, "_contexts"):
            return self  # coordinator stage; join is shard-local
        jf = _join_field(searcher.mapper)
        if jf is None or not jf.children_of(self.parent_type):
            if self.ignore_unmapped:
                return MatchNoneQuery()
            raise QueryShardException(
                "[has_parent] no join field has been configured"
                if jf is None else
                f"[has_parent] join relation [{self.parent_type}] has no "
                f"children")
        inner = self.query.rewrite(searcher)
        parent_scores: Dict[str, float] = {}
        for ctx in searcher._contexts():
            if ctx.segment.n_docs == 0:
                continue
            scores, mask = inner.execute(ctx)
            rel = _relation_docs(ctx.segment, jf.name, [self.parent_type])
            m = np.asarray(mask)[: ctx.segment.n_docs] & rel & \
                ctx.segment.live[: ctx.segment.n_docs]
            sc = np.asarray(scores)
            ids = ctx.segment.stored.ids
            for d in np.nonzero(m)[0]:
                score = float(sc[d]) if self.score else 1.0
                pid = ids[int(d)]
                parent_scores[pid] = max(parent_scores.get(pid, 0.0), score)
        q = _ParentRefScoreQuery(jf.name, jf.children_of(self.parent_type),
                                 parent_scores)
        q.boost = self.boost
        return q


class ParentIdQuery(QueryBuilder):
    """ref: ParentIdQueryBuilder — children of one specific parent doc."""

    name = "parent_id"

    def __init__(self, child_type: str, parent_id: str,
                 ignore_unmapped: bool = False):
        super().__init__()
        self.child_type = child_type
        self.parent_id = str(parent_id)
        self.ignore_unmapped = ignore_unmapped

    def rewrite(self, searcher) -> QueryBuilder:
        from elasticsearch_tpu.search.queries import MatchNoneQuery
        if not hasattr(searcher, "_contexts"):
            return self  # coordinator stage; join is shard-local
        jf = _join_field(searcher.mapper)
        if jf is None:
            if self.ignore_unmapped:
                return MatchNoneQuery()
            raise QueryShardException(
                "[parent_id] no join field has been configured")
        q = _ParentRefScoreQuery(jf.name, [self.child_type],
                                 {self.parent_id: 1.0})
        q.boost = self.boost
        return q
