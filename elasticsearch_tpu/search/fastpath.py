"""Fast-path serving engine: the Python half of the native HTTP front.

The C++ front (native/src/estpu_http.cpp) parses hot `_search` bodies and
queues (term_ids, k, filter_tids) structs; this engine drains them in
COHORTS, launches the exact batched kernel (ops/fastpath.py) on a pool of
overlapping streams, and hands (docid, score) arrays back to C++ for
response serialization. Per-REQUEST Python cost on the hot path is zero —
all Python work is per-cohort (ref: the reference's equivalent seam is the
netty event loop feeding the search threadpool,
Netty4HttpServerTransport.java + ThreadPool.java:117-181; here the
"threadpool" is a handful of launch streams because the TPU, not the host,
does the scoring).

Continuous batching emerges from backpressure: the drain thread only pulls
a new cohort when a stream is free, so under load requests accumulate in
the C++ queue and drain in full-width launches (SURVEY.md §7 hard part 5).

Eligibility (everything else falls back to the full Python path, which
serves the whole DSL): one index explicitly registered or auto-picked —
single shard, single segment, single text postings field, no security
(the fast path performs no authn/authz and must never bypass an enabled
realm chain).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.ops.device import readback as _readback
from elasticsearch_tpu.ops.plan import unpack_ids as _unpack_ids

logger = logging.getLogger("elasticsearch_tpu.fastpath")

MAX_TERMS = 16    # keep in sync with estpu_http.cpp
MAX_FILTERS = 8
Q_BATCH = 32      # cohort width (one compiled Q shape)

# process-wide serving-regime probe result ("tunnel" | "attached").
# Detached/tunneled devices (axon) switch to a degraded synchronous
# dispatch mode after the first device→host readback: every launch then
# pays a large fixed sync (~100 ms measured) plus per-lane work ~50x
# the attached device time. Serving always lives in that regime (each
# cohort reads results back), so the probe times a trivial launch
# POST-readback once per process and every FastPathServer shares it.
_REGIME: Optional[str] = None
_REGIME_LOCK = threading.Lock()
# a degraded trivial launch is ~80-120 ms; attached (or CPU test
# backends) are < 1 ms. 20 ms splits them with margin both ways.
_TUNNEL_THRESHOLD_S = 0.020


def probe_regime() -> str:
    """Decide (once per process) whether the default device serves
    launches at attached speed or through a degraded tunnel.

    Identification is by platform string FIRST: a relayed device
    (axon) degrades permanently after its first device→host readback,
    so a timing probe — which needs a readback — would itself flip the
    tunnel and then slow every pre-degraded bulk upload that follows
    (measured 850 → 16 MB/s H2D). On non-relayed platforms readbacks
    are free, so the timing probe is safe as the fallback."""
    global _REGIME
    with _REGIME_LOCK:
        if _REGIME is not None:
            return _REGIME
        import jax

        pv = ""

        def _via_extend():
            import jax.extend.backend
            return jax.extend.backend.get_backend().platform_version

        for read in (
                lambda: jax.devices()[0].client.platform_version,
                _via_extend,
                lambda: __import__(
                    "jax._src.xla_bridge", fromlist=["x"]
                ).get_backend().platform_version,
        ):
            try:
                pv = str(read()).lower()
                if pv:
                    break
            except Exception:
                continue
        if "axon" in pv:
            _REGIME = "tunnel"
            logger.info("serving regime: tunnel (relayed platform: %s)",
                        pv.split(";")[0])
            return _REGIME
        import jax.numpy as jnp

        # estpu: allow[ESTPU-JIT01] one-shot regime probe kernel, deliberately outside the tracker
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        x = jnp.ones(256, jnp.float32)
        np.asarray(f(x))          # compile; readback is free here
        best = float("inf")
        for _ in range(3):
            t0 = time.time()
            np.asarray(f(x))
            best = min(best, time.time() - t0)
        _REGIME = "tunnel" if best > _TUNNEL_THRESHOLD_S else "attached"
        logger.info("serving regime probe: %s (trivial launch %.1f ms)",
                    _REGIME, best * 1000)
        return _REGIME


def enable_compile_cache(path: Optional[str] = None):
    """Point JAX's persistent compilation cache at a stable directory so
    serving-kernel shapes compile once per machine, not once per process
    (the round-4 bench paid 242 s of warm compiles at every start), and
    attach the shape-bucket key store (telemetry/engine.py
    PersistentKernelCache) that classifies warm first-executions as
    cache hits in ``GET /_kernels`` — the warm-up-seconds-saved signal.
    Safe to call repeatedly; first caller wins."""
    import jax

    from elasticsearch_tpu.telemetry.engine import (PersistentKernelCache,
                                                    TRACKER)
    try:
        # CPU (test) backends don't need it — serving-shape compiles
        # are seconds there, and CPU AOT entries reload with machine-
        # feature warnings — the cache's value is accelerator compiles.
        # The gate reads env/config ONLY: jax.default_backend() would
        # INITIALIZE a backend, which blocks uninterruptibly on a
        # wedged relay — Node.start must never pay that just to decide
        # whether to arm telemetry.
        plats = ((os.environ.get("JAX_PLATFORMS") or "").strip()
                 or str(jax.config.jax_platforms or "").strip())
        if not plats:
            # unpinned: trust a backend that ALREADY initialized (no
            # forced init). A still-uninitialized unpinned process is
            # assumed device-bound — every cpu deployment here pins
            # (conftest, bench cpu mode, the axon site hook), so the
            # unpinned-cpu-no-backend corner only costs AOT-reload
            # warnings, never a hang.
            try:
                from jax._src import xla_bridge
                if getattr(xla_bridge, "_backends", None):
                    plats = jax.default_backend()
            except Exception:
                pass
        if plats.split(",")[0].strip().lower() == "cpu":
            return
        cur = jax.config.jax_compilation_cache_dir
        if not cur:
            cur = path or os.environ.get(
                "ESTPU_COMPILE_CACHE",
                os.path.join(os.path.expanduser("~"), ".cache",
                             "estpu_jax_cache"))
            jax.config.update("jax_compilation_cache_dir", cur)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        # the key store mirrors the executable cache at the TRACKER's
        # shape-bucket granularity (keys subdir of the same cache dir) —
        # attached even when the dir was configured elsewhere (e.g.
        # JAX_COMPILATION_CACHE_DIR): the sessions where jax's cache IS
        # active are exactly the ones whose hits must be classified
        if TRACKER.persistent is None:
            TRACKER.attach_persistent(
                PersistentKernelCache(os.path.join(cur, "keys")))
    except Exception:              # cache is an optimization only
        logger.exception("compile cache unavailable")


class FastPathServer:
    # v2 kernel term-slot count (= MAX_TERMS: every instance gets >= 1
    # slot); a bucket's slot width is bucket // N_SLOTS blocks
    N_SLOTS = 16

    def __init__(self, node, front, nb_buckets=(1024, 2048, 4096),
                 n_streams: int = 4, max_k: int = 1000,
                 ess_buckets=(256, 1024), q_batch: int = Q_BATCH,
                 kernel_mode: str = "auto", dense_mb: int = 512,
                 impact_mode: str = "certified", mesh_backend=None):
        self.node = node
        # replica-axis cohort fan-out over a device mesh (opt-in:
        # ESTPU_FASTPATH_MESH=1 resolves the node's MeshSearchBackend
        # at start, or pass one explicitly). The v1 lane's cohorts then
        # shard their Q axis over the mesh with the corpus replicated —
        # same kernel, GSPMD-partitioned, byte-identical per query.
        self.mesh_backend = mesh_backend
        self.front = front           # NativeHttpFront (owns the lib)
        self.lib = front.lib
        self.nb_buckets = tuple(sorted(nb_buckets))
        self.ess_buckets = tuple(sorted(ess_buckets))
        # "auto" (default): probe the serving regime once and pick —
        # tunnel (degraded sync dispatch) → "v1" with a TIGHT bucket
        # ladder (per-launch cost there scales with selected lanes:
        # measured 29 ms/launch at nb-256 vs 400 ms at nb-4096 under
        # 8-way overlap, 2M docs), attached → "v2m".
        # "v2m": the v1 exact kernel with the monolithic sort replaced
        # by the linear-work bitonic merge, rail dtype end-to-end — no
        # refires; wins when device work, not dispatch, dominates.
        # "v2": merge-based f32 candidates + exact f64 re-rank.
        # "v1": the monolithic-sort exact kernel everywhere.
        self.requested_mode = kernel_mode
        self.kernel_mode = kernel_mode if kernel_mode != "auto" else "v2m"
        self.regime: Optional[str] = None
        # impact-ordered block selection for queries whose block need
        # exceeds the largest lane bucket (previously: bounce to the
        # Python path). "certified": serve the impact-truncated top-k
        # only when the post-launch safe-termination check proves the
        # set exact (totals report relation "gte"); "always": serve
        # every truncated result (approximate, gte); "off": bounce.
        self.impact_mode = impact_mode
        # HBM budget for the dense hot-term tf table (θ-warm patch lane)
        self.dense_mb = int(dense_mb)
        # cohort width: one compiled Q shape; wider cohorts amortize the
        # per-launch floor at the cost of compile time and p50
        self.q_batch = int(q_batch)
        self.n_streams = n_streams
        self.max_k = max_k
        self._running = False
        self._drain_thread: Optional[threading.Thread] = None
        self._pool = None
        self._sem = threading.Semaphore(n_streams)
        # registered state
        self._lock = threading.Lock()
        # serializes whole registration passes (drain tick vs direct
        # calls) — without it two passes double-bump the generation and
        # in-flight requests parsed under the first bounce spuriously
        self._refresh_lock = threading.Lock()
        self._reg: Optional[dict] = None   # {index, field, epoch, dp, ...}
        self._gen = 0
        self._warm = False
        self.stats = {"cohorts": 0, "fast_queries": 0, "bounced": 0,
                      # θ-cache (essential-lane admission) counters —
                      # the engine-stats `caches.theta` surface
                      "theta_hits": 0, "theta_misses": 0,
                      "theta_stores": 0}
        # per-(lane, nb-bucket) dispatch counts + cohort-width histogram
        # — which warmed shapes actually serve traffic (the nb-ladder
        # tradeoff surface: GET /_kernels `serving`, bench `serving`)
        self.dispatch: Dict[str, int] = {}
        self.cohort_hist: Dict[int, int] = {}
        # warm-up accounting (persistent-compile-cache payoff)
        self.warm_seconds = 0.0
        # cohort padding accounting: every launch pads its cohort to a
        # pow2 Q row count — the pad rows are pure device waste, and
        # their share is the profile-subsystem's serving-side padding
        # attribution (the per-request analogue lives in
        # search/batching.py device records)
        self.pad_rows = 0
        self.used_rows = 0

    def _count_dispatch(self, lane: str, bucket: int, n: int):
        key = f"{lane}:{bucket}"
        self.dispatch[key] = self.dispatch.get(key, 0) + n

    def _count_cohort(self, n: int):
        b = 1
        while b < n:
            b *= 2
        self.cohort_hist[b] = self.cohort_hist.get(b, 0) + 1
        self.pad_rows += b - n
        self.used_rows += n

    def serving_stats(self) -> dict:
        """Routing/dispatch telemetry of the serving front: per-lane ×
        nb-bucket dispatch counts, cohort-width histogram, padding
        waste, warm-up seconds, and the truncated-lane counters."""
        padded = self.pad_rows + self.used_rows
        return {
            "dispatch": dict(self.dispatch),
            "cohort_hist": {str(k): v
                            for k, v in sorted(self.cohort_hist.items())},
            "padding_waste_pct": round(
                100.0 * self.pad_rows / padded, 1) if padded else 0.0,
            "warm_seconds": round(self.warm_seconds, 3),
            "nb_buckets": list(self.nb_buckets),
            "ess_buckets": list(self.ess_buckets),
            "impact_mode": self.impact_mode,
            "counters": {k: v for k, v in self.stats.items()
                         if isinstance(v, (int, float))},
        }

    def engine_cache_stats(self) -> dict:
        """θ-cache counters for the `engine.caches.theta` stats surface
        (rest/api.py nodes_stats): lane-admission hits/misses, stored
        thresholds, and the live entry count of the current
        registration (cleared with the registration on refresh)."""
        reg = self._reg
        theta = reg.get("theta") if reg is not None else None
        return {"hits": self.stats.get("theta_hits", 0),
                "misses": self.stats.get("theta_misses", 0),
                "stores": self.stats.get("theta_stores", 0),
                "entries": len(theta) if theta is not None else 0}

    # ------------------------------------------------------------ lifecycle
    def start(self):
        from concurrent.futures import ThreadPoolExecutor
        enable_compile_cache()
        if self.mesh_backend is None \
                and os.environ.get("ESTPU_FASTPATH_MESH") == "1":
            svc = getattr(self.node, "search_service", None)
            self.mesh_backend = getattr(svc, "mesh_executor", None)
        if self.requested_mode == "auto":
            try:
                self.regime = probe_regime()
            except Exception:
                logger.exception("regime probe failed; assuming attached")
                self.regime = "attached"
            if self.regime == "tunnel":
                self.kernel_mode = "v1"
                # tight ladder: degraded per-launch cost scales with
                # selected lanes, so padding a 300-block query to 4096
                # costs ~13x; overlap hides the fixed sync, so more
                # streams
                cap = self.nb_buckets[-1]
                self.nb_buckets = tuple(sorted(
                    {b for b in (256, 512, 1024, 2048, 4096)
                     if b <= cap} | {cap}))
                ecap = self.ess_buckets[-1]
                # deeper ess ladder: the lane only pays off when the
                # essential union FITS a bucket; r5 offline modeling
                # of the bench mix put the mean union at ~660 blocks
                # with a long tail past 1024
                self.ess_buckets = tuple(sorted(
                    {b for b in (256, 512, 1024, 2048)
                     if b <= ecap} | {ecap}))
                self.n_streams = max(self.n_streams, 8)
                self._sem = threading.Semaphore(self.n_streams)
            else:
                self.kernel_mode = "v2m"
            logger.info("fastpath auto mode: regime=%s kernel=%s "
                        "buckets=%s streams=%d", self.regime,
                        self.kernel_mode, self.nb_buckets, self.n_streams)
        self._pool = ThreadPoolExecutor(max_workers=self.n_streams,
                                        thread_name_prefix="fast-stream")
        self._running = True
        self._drain_thread = threading.Thread(
            target=self._drain_loop, name="fastpath-drain", daemon=True)
        self._drain_thread.start()

    def stop(self) -> bool:
        """Returns True when every thread exited (the front only frees
        its process-wide slot on a clean stop)."""
        self._running = False
        clean = True
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=3.0)
            clean = not self._drain_thread.is_alive()
            self._drain_thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        return clean

    # --------------------------------------------------------- registration
    def _eligible(self) -> Optional[Tuple[str, object]]:
        """(index_name, engine) for the best fast-servable index, or None.
        The fast path must never bypass an enabled realm chain."""
        sec = getattr(self.node, "security_service", None)
        if sec is not None and sec.enabled:
            return None
        from elasticsearch_tpu.index.mapper import TextFieldType
        best = None
        for name, idx in list(self.node.indices_service.indices.items()):
            if getattr(idx, "is_closed", False) or len(idx.shards) != 1:
                continue
            eng = idx.shards[0]
            segs = eng.segments
            if len(segs) != 1:
                continue
            seg = segs[0]
            if not seg.postings or not bool(np.all(seg.live)):
                continue
            # exactly one TEXT field with the standard analyzer (the C++
            # tokenizer mirrors it — estpu_tokenize.h); keyword subfields
            # and other fields don't interfere: a fast parse only matches
            # the registered field name
            text_fields = []
            for f in seg.postings:
                ft = idx.mapper.field_type(f)
                if isinstance(ft, TextFieldType):
                    if ft.search_analyzer_name not in ("standard",
                                                      "default"):
                        text_fields = []
                        break
                    text_fields.append(f)
            if len(text_fields) != 1:
                continue
            if best is None or seg.n_docs > best[3]:
                best = (name, idx, text_fields[0], seg.n_docs)
        return (best[0], best[1], best[2]) if best else None

    def refresh_registration(self):
        """(Re)register the fast index if its segment set changed. Called
        periodically from the drain loop — registration is C++-visible
        only AFTER the kernel shapes are warm, so a cold node never
        stalls a request on a 30s XLA compile."""
        with self._refresh_lock:
            self._refresh_registration_locked()

    def _refresh_registration_locked(self):
        pick = self._eligible()
        if pick is None:
            with self._lock:
                if self._reg is not None:
                    self.lib.es_fast_unregister(self.front.h)
                    self._reg = None
            return
        name, idx, field = pick
        eng = idx.shards[0]
        seg = eng.segments[0]
        with self._lock:
            if (self._reg is not None and self._reg["index"] == name
                    and self._reg["segment"] is seg
                    and bool(np.all(seg.live))):
                return
        pf = seg.postings[field]
        dev = idx.device_cache.get(seg)
        dp = dev.postings[field]
        # register-time enforcement of the float-pack id invariant: the
        # C++ front's readback lanes carry docids as float32 casts
        from elasticsearch_tpu.ops.plan import check_packed_id_limit
        check_packed_id_limit(dev.n_docs_padded,
                              f"fastpath register [{name}]")
        self._gen += 1
        reg = {
            "index": name, "field": field, "segment": seg,
            "gen": self._gen, "dev": dev, "dp": dp,
            "k1": idx.k1, "b": idx.b,
            "idf": None, "nb": None,
            "filter_live": {},   # filt tuple -> device (live AND filters)
            "ess_bad": set(),    # query keys whose certificate failed
        }
        # per-term idf + block counts as vectors (per-cohort selection
        # assembly is vectorized numpy, no per-term Python)
        df = dp.doc_freq.astype(np.float64)
        n = float(pf.doc_count)
        reg["idf"] = np.log1p((n - df + 0.5) / (df + 0.5)).astype(
            self._weight_dtype())
        # v2 phase A runs in f32 (candidates only); phase B re-ranks
        # with the full-precision idf above
        reg["idf32"] = reg["idf"].astype(np.float32)
        reg["nb"] = dp.term_block_count.astype(np.int64)
        reg["starts"] = dp.term_block_start.astype(np.int64)
        # --- θ-cached exact-MaxScore state (ops/fastpath.py essential
        # lane): per-term MAX possible contribution (the MaxScore upper
        # bound, from the block-max metadata), flat posting ranges for
        # the patch phase's binary search, and the θ/total cache —
        # valid for this registration's immutable segment
        from elasticsearch_tpu.index.segment import BLOCK_SIZE
        from elasticsearch_tpu.ops.plan import build_term_impacts
        k1, b = reg["k1"], reg["b"]
        starts32 = reg["starts"]
        nbv = reg["nb"]
        # per-block BM25 upper bounds + per-term impact ordering
        # (ops/plan.py): feeds BOTH the θ-lane's per-term max
        # contribution AND the budgeted impact selection of oversize
        # queries (the Lucene impact-ordered-postings analogue)
        impacts = build_term_impacts(
            starts32, nbv, pf.block_max_tf, pf.block_min_len,
            reg["idf"].astype(np.float64), float(dp.avg_len), k1, b)
        reg["impacts"] = impacts
        maxc = np.zeros(len(pf.terms), np.float64)
        nz = nbv > 0
        if nz.any():
            # a term's max contribution = its highest-impact block's
            # bound (ub_desc is impact-DESCENDING within each term)
            maxc[nz] = impacts.ub_desc[starts32[nz]]
        reg["maxc"] = maxc.astype(np.float32)
        reg["post_start"] = (starts32 * BLOCK_SIZE).astype(np.int32)
        reg["post_len"] = dp.doc_freq.astype(np.int32)
        reg["flat_docids"] = dp.block_docids.reshape(-1)
        reg["flat_tfs"] = dp.block_tfs.reshape(-1)
        reg["theta"] = {}    # (tids, filt, k) -> (θ, exact_total)
        # replica mesh for this registration's v1 cohorts: bound once so
        # warm + serve share ONE (sharded) compile signature per bucket
        reg["rmesh"] = (self.mesh_backend.replica_mesh_for(self.q_batch)
                        if self.mesh_backend is not None else None)
        t0 = time.time()
        self._build_dense_hot(reg)
        logger.info("dense hot-term build %.1fs", time.time() - t0)
        t0 = time.time()
        self._warm_shapes(reg)
        logger.info("warm shapes %.1fs", time.time() - t0)
        # only now does C++ start routing /{index}/_search to the queue
        terms_blob = b"".join(t.encode("utf-8") for t in pf.terms)
        lens = np.fromiter((len(t.encode("utf-8")) for t in pf.terms),
                           np.int64, len(pf.terms))
        offs = np.zeros(len(pf.terms) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        ids = seg.stored.ids
        id_lens = np.fromiter((len(s.encode("utf-8")) for s in ids),
                              np.int64, len(ids))
        id_offs = np.zeros(len(ids) + 1, np.int64)
        np.cumsum(id_lens, out=id_offs[1:])
        ids_blob = b"".join(s.encode("utf-8") for s in ids)
        rc = self.lib.es_fast_register(
            self.front.h, reg["gen"], reg["index"].encode(),
            field.encode(),
            terms_blob, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(pf.terms), ids_blob,
            id_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(ids), 10, self.max_k)
        if rc == 0:
            # keep blob buffers alive until C++ copies... es_fast_register
            # copies synchronously, so locals may die here
            with self._lock:
                self._reg = reg
            logger.info("fastpath registered index=%s field=%s terms=%d",
                        name, field, len(pf.terms))

    def _build_dense_hot(self, reg):
        """Dense [H, ND] tf table over the hottest terms — the θ-warm
        essential lane's patch source (ops/fastpath.py
        bm25_essential_dense_topk_batch). Non-essential terms under
        MaxScore are exactly the high-df ones, so a few hundred rows
        cover them; tf counts are exact integers, so float16 rows are
        exact up to tf 2048 (the builder falls back to float32 above
        that). Bounded by ``dense_mb`` HBM."""
        import jax

        reg["dense_tf"] = None
        reg["dense_rows"] = {}
        try:
            dp = reg["dp"]
            nd = int(dp.doc_lens.shape[0])
            df = np.asarray(reg["post_len"], np.int64)
            hot = np.nonzero(df >= max(256, nd // 256))[0]
            if len(hot) == 0:
                return
            hot = hot[np.argsort(-df[hot])]
            # HOST postings copies — the device flat arrays would pay a
            # tunnel round trip per indexed slice
            pf = dp.host
            flat_d = pf.block_docids.reshape(-1)
            flat_t = pf.block_tfs.reshape(-1)
            # dtype decided over EVERY candidate row (a mid-rank term
            # with one tf > 2048 would silently round in float16 and
            # the certificate would still stamp the wrong score ok)
            max_tf = 0.0
            for t in hot[:512]:
                s = int(reg["post_start"][t])
                ln = int(df[t])
                if ln:
                    max_tf = max(max_tf, float(flat_t[s:s + ln].max()))
            dtype = np.float16 if max_tf <= 2048 else np.float32
            budget = self.dense_mb * (1 << 20)
            h_cap = max(0, budget // (nd * np.dtype(dtype).itemsize))
            # flat gather index must stay under 2^31 (the kernel
            # computes it in int64, but x64-off deployments would wrap)
            h_cap = min(h_cap, max(1, ((1 << 31) - 1) // max(nd, 1)))
            h = int(min(len(hot), h_cap, 512))
            if h == 0:
                return
            dense = np.zeros((h, nd), dtype)
            for row, t in enumerate(hot[:h]):
                s = int(reg["post_start"][t])
                ln = int(df[t])
                dense[row, flat_d[s:s + ln]] = flat_t[s:s + ln]
                reg["dense_rows"][int(t)] = row
            t_up = time.time()
            reg["dense_tf"] = jax.device_put(dense)
            import jax as _jax
            _jax.block_until_ready(reg["dense_tf"])
            logger.info("dense table upload %.1fs (%.0f MB)",
                        time.time() - t_up, dense.nbytes / 2**20)
            logger.info("fastpath dense hot-term table: %d rows x %d "
                        "docs (%s, %.0f MB)", h, nd, dtype.__name__,
                        dense.nbytes / 2**20)
        except Exception:
            logger.exception("dense hot-term table build failed; "
                             "essential lane falls back")
            reg["dense_tf"] = None
            reg["dense_rows"] = {}

    def _warm_shapes(self, reg):
        """Compile every (Q_BATCH, nb_bucket) kernel shape up front (the
        69.7s first-query stall of round 2 — VERDICT item 2 — was lazy
        compilation on the first request). v2 mode warms the v2 shape
        per bucket plus ONE v1 shape (the largest bucket — certificate
        refires and slot-misfits run there). Compiles run CONCURRENTLY
        (XLA parallelizes across shapes — 4 serving shapes compile in
        the wall time of the slowest one) and land in the persistent
        compile cache, so a warm machine pays seconds, not minutes."""
        import jax.numpy as jnp

        from elasticsearch_tpu.ops.fastpath import (
            F_SLOTS, MAX_T, NE_SLOTS, bm25_candidates_rerank_batch,
            bm25_essential_dense_topk_batch, bm25_essential_topk_batch,
            bm25_topk_total_batch, bm25_topk_total_merge_batch)
        dp, dev = reg["dp"], reg["dev"]
        masks = jnp.stack([dev.live] * F_SLOTS)
        # cache the all-plain stack: the common no-filter cohort reuses
        # it instead of re-stacking the live columns per launch
        reg["plain_masks"] = masks
        mask_ids = np.zeros(self.q_batch, np.int32)
        wd = self._weight_dtype()
        v1_buckets = (self.nb_buckets
                      if self.kernel_mode not in ("v2", "v2m")
                      else self.nb_buckets[-1:])

        def warm_v2(nb):
            if not self._running:
                return "skipped (stopping)"
            sel = np.full((self.q_batch, nb), dp.zero_block, np.int32)
            if self.kernel_mode == "v2m":
                ws = np.zeros((self.q_batch, nb), wd)
                bm25_topk_total_merge_batch(
                    dp.block_docids, dp.block_tfs, sel, ws,
                    dp.doc_lens, masks, mask_ids, wd(dp.avg_len),
                    self.N_SLOTS, reg["k1"], reg["b"],
                    self.max_k).block_until_ready()
            else:
                ws32 = np.zeros((self.q_batch, nb), np.float32)
                bm25_candidates_rerank_batch(
                    dp.block_docids, dp.block_tfs, reg["flat_docids"],
                    reg["flat_tfs"], sel, ws32, dp.doc_lens, masks,
                    mask_ids,
                    np.zeros((self.q_batch, MAX_T), np.int32),
                    np.zeros((self.q_batch, MAX_T), np.int32),
                    np.zeros((self.q_batch, MAX_T), wd),
                    wd(dp.avg_len), self.N_SLOTS, reg["k1"], reg["b"],
                    self.max_k).block_until_ready()
            return f"{self.kernel_mode} NB={nb}"

        def warm_v1(nb):
            if not self._running:
                return "skipped (stopping)"
            sel = np.full((self.q_batch, nb), dp.zero_block, np.int32)
            ws = np.zeros((self.q_batch, nb), wd)
            bd, bt, s_, w_, dl, mk, mi = self._v1_inputs(
                reg, sel, ws, masks, mask_ids)
            bm25_topk_total_batch(
                bd, bt, s_, w_, dl, mk, mi, wd(dp.avg_len), reg["k1"],
                reg["b"], self.max_k).block_until_ready()
            return f"v1 NB={nb}" + (
                " (mesh)" if reg.get("rmesh") is not None else "")

        def warm_ess_dense(nb):
            if not self._running:
                return "skipped (stopping)"
            sel = np.full((self.q_batch, nb), dp.zero_block, np.int32)
            ws = np.zeros((self.q_batch, nb), wd)
            bm25_essential_dense_topk_batch(
                dp.block_docids, dp.block_tfs, reg["dense_tf"],
                sel, ws, dp.doc_lens, masks, mask_ids,
                np.full((self.q_batch, NE_SLOTS), -1, np.int32),
                np.zeros((self.q_batch, NE_SLOTS), wd),
                np.zeros(self.q_batch, wd),
                wd(dp.avg_len), reg["k1"], reg["b"],
                self.max_k).block_until_ready()
            return f"essD NB={nb}"

        def warm_ess_binary(nb):
            if not self._running:
                return "skipped (stopping)"
            sel = np.full((self.q_batch, nb), dp.zero_block, np.int32)
            ws = np.zeros((self.q_batch, nb), wd)
            bm25_essential_topk_batch(
                dp.block_docids, dp.block_tfs, reg["flat_docids"],
                reg["flat_tfs"], sel, ws, dp.doc_lens, masks, mask_ids,
                np.zeros((self.q_batch, NE_SLOTS), np.int32),
                np.zeros((self.q_batch, NE_SLOTS), np.int32),
                np.zeros((self.q_batch, NE_SLOTS), wd),
                np.zeros(self.q_batch, wd),
                wd(dp.avg_len), reg["k1"], reg["b"],
                self.max_k).block_until_ready()
            return f"ess NB={nb}"

        jobs = []
        for nb in (self.nb_buckets if self.kernel_mode in ("v2", "v2m")
                   else ()):
            jobs.append((warm_v2, nb))
        for nb in v1_buckets:
            jobs.append((warm_v1, nb))
        # warm EXACTLY the essential kernels the router can reach
        # (warming fewer reintroduces the round-2 serve-time compile
        # stall; warming more burns startup on dead code):
        # tunnel+dense → dense only (binary patch is unreachable);
        # tunnel without dense → lane disabled, warm nothing;
        # attached+dense → BOTH (mixed cohorts demote to binary);
        # attached without dense → binary only.
        has_dense = reg.get("dense_tf") is not None
        for nb in self.ess_buckets:
            if has_dense:
                jobs.append((warm_ess_dense, nb))
                if self.regime != "tunnel":
                    jobs.append((warm_ess_binary, nb))
            elif self.regime != "tunnel":
                jobs.append((warm_ess_binary, nb))
        from concurrent.futures import ThreadPoolExecutor

        # 4 workers: XLA's internal compile parallelism saturates the
        # host around there, and a stop() during warm only has to drain
        # 4 in-flight compiles (queued jobs see _running and skip)
        t0 = time.time()
        try:
            with ThreadPoolExecutor(
                    max_workers=min(4, max(1, len(jobs)))) as ex:
                futs = [ex.submit(fn, nb) for fn, nb in jobs
                        if self._running]
                for f in futs:
                    try:
                        logger.info("fastpath warm %s (t+%.1fs)",
                                    f.result(), time.time() - t0)
                    except Exception:
                        logger.exception("fastpath warm compile failed")
        except RuntimeError:
            # interpreter shutdown while the drain thread was still
            # registering — nothing to warm for, just exit quietly
            if self._running:
                raise
        finally:
            # warm-ladder wall time: with the persistent compile cache
            # warm, this drops from minutes (cold XLA compiles) to the
            # executable-deserialize cost — `serving.warm_seconds`
            self.warm_seconds += time.time() - t0

    # --------------------------------------------------------------- drain
    def _drain_loop(self):
        c = ctypes
        # drain DEEP: the router groups by bucket class before chunking
        # to q_batch, so a shallow poll fragments cohorts across the
        # bucket ladder (r5 full bench averaged 19.7/32 at 2x); deep
        # polls give every bucket group a shot at full cohorts
        max_n = 8 * self.q_batch
        tokens = (c.c_uint64 * max_n)()
        gens = (c.c_int32 * max_n)()
        ks = (c.c_int32 * max_n)()
        nterms = (c.c_int32 * max_n)()
        tids = (c.c_int32 * (max_n * MAX_TERMS))()
        nfilt = (c.c_int32 * max_n)()
        ftids = (c.c_int32 * (max_n * MAX_FILTERS))()
        last_reg_check = 0.0
        while self._running:
            now = time.time()
            if now - last_reg_check > 1.0:
                last_reg_check = now
                try:
                    self.refresh_registration()
                except Exception:
                    logger.exception("fastpath registration failed")
            h = self.front.h
            if h is None:
                break
            n = self.lib.es_fast_poll(h, tokens, gens, ks, nterms, tids,
                                      nfilt, ftids, max_n, 50)
            if n == 0:
                continue
            try:
                self._route_cohort(h, n, tokens, gens, ks, nterms, tids,
                                   nfilt, ftids)
            except Exception:
                # the drain thread must NEVER die: C++ keeps routing to
                # the fast queue and every client would hang
                logger.exception("fastpath drain error; bouncing batch")
                for i in range(n):
                    try:
                        self.lib.es_fast_bounce(h, tokens[i])
                    except Exception:
                        pass

    def _route_cohort(self, h, n, tokens, gens, ks, nterms, tids, nfilt,
                      ftids):
        t_arrive = time.time()
        reqs = []
        for i in range(n):
            reqs.append((
                tokens[i], gens[i], ks[i],
                list(tids[i * MAX_TERMS:
                          i * MAX_TERMS + nterms[i]]),
                tuple(sorted(ftids[i * MAX_FILTERS:
                                   i * MAX_FILTERS + nfilt[i]])),
            ))
        with self._lock:
            reg = self._reg
        if reg is None:
            for tok, *_ in reqs:
                self.lib.es_fast_bounce(h, tok)
            return
        # group by NB bucket only — filter sets ride per-query mask
        # rows inside one launch (ops/fastpath.py F_SLOTS). Queries with
        # a cached θ route to the essential lane: a MUCH smaller sort
        # plus per-candidate patching (exact MaxScore). Everything else
        # rides the v2 merge kernel when it fits the slot layout;
        # slot-misfits and certificate refires use the v1 full kernel.
        by_bucket: Dict[int, list] = {}
        v2_by_bucket: Dict[int, list] = {}
        ess_by_bucket: Dict[int, list] = {}
        trunc_items: list = []
        for tok, gen, k, term_ids, filt in reqs:
            if gen != reg["gen"]:
                # parsed under an older term dictionary (segment changed
                # between parse and drain) — term ids are meaningless now
                self.stats["bounced"] += 1
                self.lib.es_fast_bounce(h, tok)
                continue
            nb_need = int(reg["nb"][[t for t in term_ids
                                     if t >= 0]].sum()) \
                if any(t >= 0 for t in term_ids) else 0
            bucket = None
            for nb in self.nb_buckets:
                if nb_need <= nb:
                    bucket = nb
                    break
            if bucket is None or not term_ids:
                # empty query: cheap immediate answer, no device work
                if not term_ids or all(t < 0 for t in term_ids):
                    self._respond_empty(tok, reg)
                    continue
                # oversize selection: impact-ordered truncation to the
                # largest bucket (the blocks with the highest score
                # upper bounds enter the budget; the excluded tail's
                # residual bound rides along for the post-launch
                # safe-termination check) instead of the old
                # unconditional bounce to the slow Python path. In
                # "certified" mode a k == max_k query can never certify
                # (the check needs the (k+1)-th observed score and the
                # kernel returns exactly max_k) — bounce immediately
                # rather than pay a doomed launch.
                attempt = (self.impact_mode == "always"
                           or (self.impact_mode == "certified"
                               and k < self.max_k
                               and not self._trunc_hopeless(reg)))
                trunc = self._impact_truncate(reg, term_ids) \
                    if attempt else None
                if trunc is None:
                    self.stats["bounced"] += 1
                    self.lib.es_fast_bounce(h, tok)
                else:
                    trunc_items.append((tok, k, term_ids, filt, trunc))
                continue
            ess = self._essential_split(reg, k, term_ids, filt,
                                        nb_need)
            if ess is not None:
                ess_by_bucket.setdefault(ess[0], []).append(
                    (tok, k, term_ids, filt, ess))
                continue
            if self.kernel_mode in ("v2", "v2m"):
                b2 = self._v2_bucket(reg, term_ids)
                if b2 is not None:
                    v2_by_bucket.setdefault(b2, []).append(
                        (tok, k, term_ids, filt))
                    continue
                # slot misfit: only the LARGEST v1 shape is warm in v2
                # mode — routing to the original (smaller) bucket would
                # lazy-compile at serve time (the round-2 stall)
                bucket = self.nb_buckets[-1]
            by_bucket.setdefault(bucket, []).append(
                (tok, k, term_ids, filt))
        # adaptive merge-up: a nearly-empty bucket group pays the full
        # per-launch tunnel floor for a handful of queries — fold small
        # groups into the next bigger bucket (padding costs device time
        # only when the group was too small to amortize the floor anyway)
        def merge_up(groups):
            merged: Dict[int, list] = {}
            carry: list = []
            for bucket in sorted(groups):
                cur = carry + groups[bucket]
                if len(cur) < self.q_batch // 2 \
                        and bucket != self.nb_buckets[-1] \
                        and any(b > bucket for b in groups):
                    carry = cur
                    continue
                merged.setdefault(bucket, []).extend(cur)
                carry = []
            # the max bucket can never carry (the carry condition
            # requires a bigger bucket to exist)
            assert not carry
            return merged

        # the θ-warm lane fragments worst without folding: the ess
        # ladder splits the SAME query stream three ways, and a 10-deep
        # cohort pays the identical launch floor a 32-deep one does
        # (r5 full-bench measured avg cohort 16.3/32 before this fold)
        for bucket, items in merge_up(ess_by_bucket).items():
            for chunk in self._chunk_by_slots(items):
                stack, rows = self._resolve_mask_rows(
                    reg, {it[3] for it in chunk})
                self._count_dispatch("ess", bucket, len(chunk))
                self._count_cohort(len(chunk))
                self._sem.acquire()
                self._pool.submit(self._launch_essential, reg, bucket,
                                  chunk, t_arrive, stack, rows)

        for bucket, items in merge_up(v2_by_bucket).items():
            for chunk in self._chunk_by_slots(items):
                stack, rows = self._resolve_mask_rows(
                    reg, {it[3] for it in chunk})
                self._count_dispatch(self.kernel_mode, bucket,
                                     len(chunk))
                self._count_cohort(len(chunk))
                self._sem.acquire()
                self._pool.submit(self._launch_group_v2, reg, bucket,
                                  chunk, t_arrive, stack, rows)
        for bucket, items in merge_up(by_bucket).items():
            for chunk in self._chunk_by_slots(items):
                stack, rows = self._resolve_mask_rows(
                    reg, {it[3] for it in chunk})
                self._count_dispatch("v1", bucket, len(chunk))
                self._count_cohort(len(chunk))
                # backpressure: wait for a free stream — requests keep
                # queueing in C++ meanwhile and drain in wider cohorts
                self._sem.acquire()
                self._pool.submit(self._launch_group, reg, bucket,
                                  chunk, t_arrive, stack, rows)
        if trunc_items:
            # the truncated lane runs on the largest warm v1 shape
            # (order-agnostic kernel: the impact-chosen subset needs no
            # slot layout)
            bucket = self.nb_buckets[-1]
            for chunk in self._chunk_by_slots(trunc_items):
                stack, rows = self._resolve_mask_rows(
                    reg, {it[3] for it in chunk})
                self._count_dispatch("trunc", bucket, len(chunk))
                self._count_cohort(len(chunk))
                self._sem.acquire()
                self._pool.submit(self._launch_truncated, reg, bucket,
                                  chunk, t_arrive, stack, rows)

    def _v2_bucket(self, reg, term_ids) -> Optional[int]:
        """Smallest bucket whose slot layout fits: each term INSTANCE
        starts on a slot boundary (slot = bucket // N_SLOTS blocks), so
        the fit condition is sum(ceil(blocks_t / slot)) <= N_SLOTS."""
        nbs = reg["nb"]
        cnts = [int(nbs[t]) for t in term_ids if t >= 0]
        if not cnts or len(cnts) > self.N_SLOTS:
            return None
        for bucket in self.nb_buckets:
            slot = bucket // self.N_SLOTS
            if slot == 0:
                continue
            if sum(-(-c // slot) for c in cnts) <= self.N_SLOTS:
                return bucket
        return None

    def _launch_group_v2(self, reg, bucket, items, t_arrive, stack,
                         rows):
        try:
            self._launch_group_v2_inner(reg, bucket, items, t_arrive,
                                        stack, rows)
        except Exception:
            logger.exception("fastpath v2 launch failed; bouncing "
                             "cohort")
            h = self.front.h
            for tok, *_ in items:
                try:
                    if h is not None:
                        self.lib.es_fast_bounce(h, tok)
                except Exception:
                    pass
        finally:
            self._sem.release()

    def _launch_group_v2_inner(self, reg, bucket, items, t_arrive,
                               stack, rows):
        from elasticsearch_tpu.ops.fastpath import (
            MAX_T, bm25_candidates_rerank_batch,
            bm25_topk_total_merge_batch)
        dp = reg["dp"]
        slot = bucket // self.N_SLOTS
        v2m = self.kernel_mode == "v2m"
        q = len(items)
        sel = np.full((self.q_batch, bucket), dp.zero_block, np.int32)
        ws = np.zeros((self.q_batch, bucket),
                      self._weight_dtype() if v2m else np.float32)
        ts = np.zeros((self.q_batch, MAX_T), np.int32)
        tl = np.zeros((self.q_batch, MAX_T), np.int32)
        ti = np.zeros((self.q_batch, MAX_T), self._weight_dtype())
        mask_ids = np.zeros(self.q_batch, np.int32)
        starts, nbs = reg["starts"], reg["nb"]
        idf32, idf = reg["idf32"], reg["idf"]
        wsrc = idf if v2m else idf32
        no_match: list = []
        for qi, (tok, k, term_ids, filt) in enumerate(items):
            pos = 0
            ninst = 0
            for t in term_ids:
                if t < 0:
                    continue
                cnt = int(nbs[t])
                s = int(starts[t])
                sel[qi, pos:pos + cnt] = np.arange(s, s + cnt,
                                                   dtype=np.int32)
                ws[qi, pos:pos + cnt] = wsrc[t]
                ts[qi, ninst] = reg["post_start"][t]
                tl[qi, ninst] = reg["post_len"][t]
                ti[qi, ninst] = idf[t]
                ninst += 1
                pos += -(-cnt // slot) * slot
            if filt:
                row = rows.get(filt)
                if row is None:          # unknown filter term ⇒ no hits
                    no_match.append(tok)
                    sel[qi, :] = dp.zero_block
                    ws[qi, :] = 0.0
                    tl[qi, :] = 0
                    continue
                mask_ids[qi] = row
        masks = stack
        k_static = self.max_k
        if v2m:
            packed = bm25_topk_total_merge_batch(
                dp.block_docids, dp.block_tfs, sel, ws, dp.doc_lens,
                masks, mask_ids, self._weight_dtype()(dp.avg_len),
                self.N_SLOTS, reg["k1"], reg["b"], k_static)
        else:
            packed = bm25_candidates_rerank_batch(
                dp.block_docids, dp.block_tfs, reg["flat_docids"],
                reg["flat_tfs"], sel, ws, dp.doc_lens, masks, mask_ids,
                ts, tl, ti, self._weight_dtype()(dp.avg_len),
                self.N_SLOTS, reg["k1"], reg["b"], k_static)
        # ONE device→host sync per cohort, through the tracked funnel
        out = _readback("search.fastpath.v2_cohort", packed)
        took_ms = int((time.time() - t_arrive) * 1000)
        self.stats["cohorts"] += 1
        self.stats["v2_queries"] = self.stats.get("v2_queries", 0) + q
        no_match_set = set(no_match)
        refire: list = []
        for qi, (tok, k, term_ids, filt) in enumerate(items):
            if tok in no_match_set:
                self._respond_empty(tok, reg)
                continue
            tail = out[qi, 2 * k_static:]
            total = int(tail[0])
            if not v2m and not int(tail[1]):
                refire.append((tok, k, term_ids, filt))
                continue
            vals = out[qi, :k_static]
            ids = _unpack_ids(out[qi, k_static:2 * k_static])
            nhit = int(min(k, np.isfinite(vals).sum()))
            v = vals[:nhit]
            d = ids[:nhit]
            if v2m:
                # v2m's device top_k tie order is arbitrary (v1
                # contract): re-sort (score desc, docid asc) host-side
                order = np.lexsort((d, -v))
                v, d = v[order], d[order]
            self._respond_hits(reg, tok, np.ascontiguousarray(v),
                               np.ascontiguousarray(d),
                               k, total, took_ms, term_ids, filt)
        self.stats["fast_queries"] += q - len(refire)
        if refire:
            # uncertified (score-tie mass wider than the candidate set)
            # — the exact v1 kernel serves them; already holding a
            # stream permit, run inline at the v1-warm bucket
            self.stats["v2_refires"] = self.stats.get("v2_refires", 0) \
                + len(refire)
            self._launch_group_inner(reg, self.nb_buckets[-1], refire,
                                     t_arrive, stack, rows)

    def _respond_empty(self, tok, reg):
        empty = np.zeros(0, np.int32)
        h = self.front.h
        if h is None:
            return
        self.lib.es_fast_respond(
            h, tok, reg["index"].encode(),
            empty.ctypes.data_as(ctypes.c_void_p),
            empty.ctypes.data_as(ctypes.c_void_p), 0, 0, b"eq", 0)

    # -------------------------------------------------------------- launch
    def _launch_group(self, reg, bucket, items, t_arrive, stack,
                      rows):
        try:
            self._launch_group_inner(reg, bucket, items, t_arrive,
                                     stack, rows)
        except Exception:
            logger.exception("fastpath launch failed; bouncing cohort")
            h = self.front.h
            for tok, *_ in items:
                try:
                    if h is not None:
                        self.lib.es_fast_bounce(h, tok)
                except Exception:
                    pass
        finally:
            self._sem.release()

    # ------------------------------------------------- impact truncation
    # adaptive back-off: a registration whose certificate NEVER fires
    # (boundary-dense corpora refuse nearly everything the doom check
    # lets through) stops paying uncertifiable launches and bounces
    # directly until the next registration resets the counters
    TRUNC_BACKOFF_ATTEMPTS = 32

    def _trunc_hopeless(self, reg) -> bool:
        if (reg.get("trunc_attempts", 0) >= self.TRUNC_BACKOFF_ATTEMPTS
                and reg.get("trunc_certified", 0) == 0):
            self.stats["trunc_backoff"] = \
                self.stats.get("trunc_backoff", 0) + 1
            return True
        return False

    def _impact_truncate(self, reg, term_ids):
        """Budgeted impact-ordered selection for a query whose full
        block need exceeds the largest bucket. Returns (known_terms,
        per-term block arrays, miss_bound) or None when the query has
        no known terms (the caller bounces)."""
        known = [t for t in term_ids if t >= 0]
        if not known or reg.get("impacts") is None:
            return None
        from elasticsearch_tpu.ops.plan import select_blocks_impact
        per_term, miss = select_blocks_impact(
            known, self.nb_buckets[-1], reg["starts"], reg["nb"],
            reg["impacts"])
        if self.impact_mode == "certified" and miss > 0.0:
            # pre-launch doom check: certification needs miss < kth,
            # and no observed score can exceed Σ per-kept-term best
            # SELECTED bound — which is maxc for every term that kept
            # ≥1 block (greedy selection keeps a term's top-impact
            # blocks first). A selection that provably can't certify
            # bounces NOW instead of paying a doomed launch+readback
            # (the heavily-truncated multi-term case).
            obs_max = sum(float(reg["maxc"][t])
                          for t, blocks in zip(known, per_term)
                          if len(blocks))
            if miss >= obs_max:
                self.stats["trunc_doomed"] = \
                    self.stats.get("trunc_doomed", 0) + 1
                return None
        return known, per_term, miss

    def _launch_truncated(self, reg, bucket, items, t_arrive, stack,
                          rows):
        try:
            self._launch_truncated_inner(reg, bucket, items, t_arrive,
                                         stack, rows)
        except Exception:
            logger.exception("truncated launch failed; bouncing cohort")
            h = self.front.h
            for tok, *_ in items:
                try:
                    if h is not None:
                        self.lib.es_fast_bounce(h, tok)
                except Exception:
                    pass
        finally:
            self._sem.release()

    def _launch_truncated_inner(self, reg, bucket, items, t_arrive,
                                stack, rows):
        """Impact-truncated cohort on the exact v1 kernel: scores are
        exact over the SELECTED blocks, so every observed score is a
        lower bound of the true score and no doc can gain more than the
        query's ``miss_bound`` (ops/plan.select_blocks_impact). The
        post-launch safe-termination check proves (when it can) that
        the observed top-k SET is the true top-k; totals always report
        relation "gte" (excluded blocks may hold unseen matches)."""
        from elasticsearch_tpu.ops.fastpath import bm25_topk_total_batch
        from elasticsearch_tpu.ops.plan import impact_safe_termination
        dp = reg["dp"]
        sel = np.full((self.q_batch, bucket), dp.zero_block, np.int32)
        ws = np.zeros((self.q_batch, bucket), self._weight_dtype())
        mask_ids = np.zeros(self.q_batch, np.int32)
        idf = reg["idf"]
        no_match: list = []
        for qi, (tok, k, term_ids, filt, trunc) in enumerate(items):
            known, per_term, _miss = trunc
            pos = 0
            for t, blocks in zip(known, per_term):
                cnt = len(blocks)
                sel[qi, pos:pos + cnt] = blocks
                ws[qi, pos:pos + cnt] = idf[t]
                pos += cnt
            if filt:
                row = rows.get(filt)
                if row is None:          # unknown filter term ⇒ no hits
                    no_match.append(tok)
                    sel[qi, :] = dp.zero_block
                    ws[qi, :] = 0.0
                    continue
                mask_ids[qi] = row
        k_static = self.max_k
        bd, bt, sel_m, ws_m, dl, mk, mi = self._v1_inputs(
            reg, sel, ws, stack, mask_ids)
        packed = bm25_topk_total_batch(
            bd, bt, sel_m, ws_m, dl, mk, mi,
            self._weight_dtype()(dp.avg_len), reg["k1"],
            reg["b"], k_static)
        # ONE device→host sync per cohort, through the tracked funnel
        out = _readback("search.fastpath.truncated_cohort", packed)
        took_ms = int((time.time() - t_arrive) * 1000)
        self.stats["cohorts"] += 1
        if self._mesh_active(reg):
            self.stats["mesh_cohorts"] = \
                self.stats.get("mesh_cohorts", 0) + 1
            self.mesh_backend._dispatch("replica", len(items))
        h = self.front.h
        idx_b = reg["index"].encode()
        no_match_set = set(no_match)
        served = 0
        for qi, (tok, k, term_ids, filt, trunc) in enumerate(items):
            if tok in no_match_set:
                self._respond_empty(tok, reg)
                served += 1
                continue
            miss = float(trunc[2])
            vals = out[qi, :k_static]
            ids = _unpack_ids(out[qi, k_static:2 * k_static])
            total = int(out[qi, 2 * k_static:][0])
            nhit = int(min(k, np.isfinite(vals).sum()))
            certified = False
            if nhit >= k:
                kth = float(vals[k - 1])
                if k < k_static:
                    # the (k+1)-th observed score bounds the best
                    # excluded candidate
                    nxt = (float(vals[k])
                           if np.isfinite(vals[k]) else 0.0)
                elif total <= k:
                    # every matching doc is in the result: only
                    # entirely-unseen docs (observed 0) could displace
                    nxt = 0.0
                else:
                    nxt = None   # k == kernel k: no (k+1)-th to bound by
                certified = (nxt is not None
                             and impact_safe_termination(kth, nxt, miss))
            # per-registration certificate track record (feeds the
            # _trunc_hopeless back-off; refresh resets with the reg)
            reg["trunc_attempts"] = reg.get("trunc_attempts", 0) + 1
            if certified:
                reg["trunc_certified"] = \
                    reg.get("trunc_certified", 0) + 1
            if not certified and self.impact_mode != "always":
                # can't prove the truncated set exact — the full Python
                # path serves it (the pre-impact behavior for oversize)
                self.stats["trunc_refused"] = \
                    self.stats.get("trunc_refused", 0) + 1
                self.stats["bounced"] += 1
                if h is not None:
                    self.lib.es_fast_bounce(h, tok)
                continue
            v = vals[:nhit]
            d = ids[:nhit]
            order = np.lexsort((d, -v))
            v = np.ascontiguousarray(v[order])
            d = np.ascontiguousarray(d[order])
            self.stats["trunc_served"] = \
                self.stats.get("trunc_served", 0) + 1
            if certified:
                self.stats["trunc_certified"] = \
                    self.stats.get("trunc_certified", 0) + 1
            served += 1
            if h is None:
                return
            self.lib.es_fast_respond(
                h, tok, idx_b,
                d.ctypes.data_as(ctypes.c_void_p),
                v.ctypes.data_as(ctypes.c_void_p),
                nhit, total, b"gte", took_ms)
        self.stats["fast_queries"] += served

    # binary-search depth contract of the patch kernel (ops/fastpath)
    NE_MAX_LEN = 1 << 21

    @staticmethod
    def _weight_dtype():
        """Weights/avg ride the ranking dtype: under x64 the kernels
        rank in float64, and f32-ROUNDED idf weights would reintroduce
        the ~2^-24 boundary noise the f64 rail removes."""
        import jax
        return np.float64 if jax.config.jax_enable_x64 else np.float32

    def _chunk_by_slots(self, items):
        """Split a launch class into cohorts bounded by the cohort
        width (Q_BATCH) AND the mask-slot budget (≤ F_SLOTS-1 distinct
        filter sets per launch; row 0 is the plain live mask). Item
        layout: (tok, k, term_ids, filt, ...)."""
        from elasticsearch_tpu.ops.fastpath import F_SLOTS
        chunk: list = []
        filts: set = set()
        for item in items:
            f = item[3]
            nf = filts | ({f} if f else set())
            if chunk and (len(chunk) >= self.q_batch
                          or len(nf) > F_SLOTS - 1):
                yield chunk
                chunk = []
                filts = set()
                nf = {f} if f else set()
            chunk.append(item)
            filts = nf
        if chunk:
            yield chunk

    def _essential_split(self, reg, k, term_ids, filt,
                         nb_full=None):
        """(ess_bucket, ess_terms, ne_terms, ne_bound, θ, total) when a
        cached θ licenses the essential lane for this exact query, else
        None. Term INSTANCES partition (duplicates keep their own
        slot — a doubled term doubles both its contribution and its
        bound)."""
        from elasticsearch_tpu.ops.fastpath import NE_SLOTS
        if k != self.max_k:
            return None
        key = (tuple(term_ids), filt, k)
        hit = reg["theta"].get(key)
        if hit is None:
            self.stats["theta_misses"] = \
                self.stats.get("theta_misses", 0) + 1
            return None
        self.stats["theta_hits"] = self.stats.get("theta_hits", 0) + 1
        theta, total = hit
        if key in reg["ess_bad"]:
            # certificate already failed once for this query — the
            # essential attempt + refire would only double the work
            return None
        known = [t for t in term_ids if t >= 0]
        if len(known) < 2:
            return None
        use_dense = reg.get("dense_tf") is not None
        if self.regime == "tunnel" and not use_dense:
            # the binary-search patch kernel is ~170 DEPENDENT gathers —
            # in the tunnel's degraded sync-dispatch mode that costs
            # MORE than the full kernel it replaces (measured 862 vs
            # 499 ms/launch at 2M docs); without the dense table the
            # lane is a pessimization there
            return None
        dense_rows = reg.get("dense_rows") or {}
        maxc = reg["maxc"]
        inst = sorted(known, key=lambda t: float(maxc[t]))
        # a FRACTION of θ, not all of it: correctness only needs
        # Σ maxc_ne < θ (docs outside every essential list can't reach
        # the kth), and the CERTIFICATE needs ess_(C+1) + Σ maxc_ne <
        # kth. With the candidate budget at CAND=16K the overflow term
        # is usually -inf and kth == θ for a repeat query, so 0.9
        # keeps a real margin while TRIPLING lane eligibility vs the
        # old 0.5 (offline model on the bench mix: 41 -> 119 of 256
        # queries, mean essential union 2107 -> 663 blocks); failed
        # certificates memoize into ess_bad and never retry
        theta_safe = float(theta) * 0.9
        ne: list = []
        bound = 0.0
        ess: list = []
        for t in inst:
            mc = float(maxc[t])
            # a term can ride an NE slot only if the patch phase can
            # recover its per-candidate tf. Tunnel: dense table row
            # ONLY (binary search is the poison being avoided).
            # Attached: the pre-dense contract — a binary-searchable
            # flat range (STRICT 2^21: the patch kernel's 21 halving
            # steps only fully resolve ranges < 2^21); the launch then
            # upgrades to the dense kernel when every NE term of the
            # cohort happens to have a row.
            if self.regime == "tunnel":
                patchable = t in dense_rows
            else:
                patchable = int(reg["post_len"][t]) < self.NE_MAX_LEN
            if (len(ne) < NE_SLOTS and len(inst) - len(ne) > 1
                    and bound + mc < theta_safe and patchable):
                ne.append(t)
                bound += mc
            else:
                ess.append(t)
        if not ne:
            return None
        # the certificate only closes trivially when EVERY matching doc
        # of the essential union is a candidate (overflow bound -inf);
        # past the candidate budget the bound engages and, at 0.9·θ
        # admission, almost always refires (r5 run: 78 of 100 lane
        # launches refired before this gate). Union size is bounded by
        # Σ df over essential terms.
        from elasticsearch_tpu.ops.fastpath import CAND as _CAND
        if int(reg["post_len"][ess].sum()) > int(0.9 * _CAND):
            return None
        nb_ess = int(reg["nb"][ess].sum())
        if nb_full is None:
            nb_full = int(reg["nb"][known].sum())
        if nb_ess * 5 > nb_full * 4:
            # under a 1.25x reduction the lane's fixed costs (extra
            # top-(C+1), patch pass, refire risk) outweigh the win —
            # in the tunnel regime per-launch cost ~ lanes, so even
            # modest reductions pay
            return None
        for bkt in self.ess_buckets:
            if nb_ess <= bkt:
                return (bkt, ess, ne, bound, float(theta), int(total))
        return None

    def _launch_essential(self, reg, bucket, items, t_arrive, stack,
                          rows):
        responded: set = set()
        try:
            self._launch_essential_inner(reg, bucket, items, t_arrive,
                                         stack, rows, responded)
        except Exception:
            logger.exception("essential launch failed; full-kernel "
                             "retry")
            # only tokens not yet answered — a mid-loop failure must
            # never double-respond/bounce consumed tokens
            left = [it for it in items if it[0] not in responded]
            try:
                if left:
                    self._refire_full(reg, left, t_arrive, stack, rows)
            except Exception:
                h = self.front.h
                for tok, *_ in left:
                    try:
                        if h is not None:
                            self.lib.es_fast_bounce(h, tok)
                    except Exception:
                        pass
        finally:
            self._sem.release()

    def _refire_full(self, reg, items, t_arrive, stack, rows):
        """Uncertified/failed essential queries re-run on the exact full
        kernel (already holding a stream permit — run inline)."""
        full_items = [(tok, k, term_ids, filt)
                      for tok, k, term_ids, filt, _ess in items]
        bucket = self.nb_buckets[-1]
        if self.kernel_mode not in ("v2", "v2m"):
            # only v1 mode warms the smaller v1 shapes; in v2/v2m the
            # largest is the ONLY warmed v1 shape (lazy-compiling a
            # smaller one at serve time is the round-2 stall)
            nb_need = max(
                int(reg["nb"][[t for t in tids if t >= 0]].sum())
                for _tok, _k, tids, _f in full_items)
            for nb in self.nb_buckets:
                if nb_need <= nb:
                    bucket = nb
                    break
        self.stats["ess_refires"] = self.stats.get("ess_refires", 0) \
            + len(full_items)
        self._launch_group_inner(reg, bucket, full_items, t_arrive,
                                 stack, rows)

    def _launch_essential_inner(self, reg, bucket, items, t_arrive,
                                stack, rows, responded=None):
        from elasticsearch_tpu.ops.fastpath import (
            NE_SLOTS, bm25_essential_dense_topk_batch,
            bm25_essential_topk_batch)
        dp = reg["dp"]
        use_dense = reg.get("dense_tf") is not None
        sel = np.full((self.q_batch, bucket), dp.zero_block,
                      np.int32)
        ws = np.zeros((self.q_batch, bucket), self._weight_dtype())
        mask_ids = np.zeros(self.q_batch, np.int32)
        ne_start = np.zeros((self.q_batch, NE_SLOTS), np.int32)
        ne_len = np.zeros((self.q_batch, NE_SLOTS), np.int32)
        ne_row = np.full((self.q_batch, NE_SLOTS), -1, np.int32)
        ne_idf = np.zeros((self.q_batch, NE_SLOTS), self._weight_dtype())
        ne_bound = np.zeros(self.q_batch, self._weight_dtype())
        starts, nbs, idf = reg["starts"], reg["nb"], reg["idf"]
        dense_rows = reg.get("dense_rows") or {}
        bad: list = []
        for qi, (tok, k, term_ids, filt, essd) in enumerate(items):
            _bkt, ess_terms, ne_terms, bound, theta, total = essd
            pos = 0
            for t in ess_terms:
                cnt = int(nbs[t])
                st = int(starts[t])
                sel[qi, pos:pos + cnt] = np.arange(st, st + cnt,
                                                   dtype=np.int32)
                ws[qi, pos:pos + cnt] = idf[t]
                pos += cnt
            for ti, t in enumerate(ne_terms):
                # fill BOTH patch descriptors; the cohort upgrades to
                # the dense kernel only when EVERY NE term resolved a
                # row (attached-mode splits admit binary-only terms)
                row = dense_rows.get(t, -1)
                ne_row[qi, ti] = row
                if row < 0:
                    use_dense = False
                ne_start[qi, ti] = reg["post_start"][t]
                ne_len[qi, ti] = reg["post_len"][t]
                ne_idf[qi, ti] = idf[t]
            ne_bound[qi] = bound
            if filt:
                row = rows.get(filt)
                if row is None:
                    bad.append(tok)
                    sel[qi, :] = dp.zero_block
                    ws[qi, :] = 0.0
                    continue
                mask_ids[qi] = row
        masks = stack
        k_static = self.max_k
        if use_dense:
            packed = bm25_essential_dense_topk_batch(
                dp.block_docids, dp.block_tfs, reg["dense_tf"],
                sel, ws, dp.doc_lens, masks, mask_ids,
                ne_row, ne_idf, ne_bound,
                self._weight_dtype()(dp.avg_len), reg["k1"], reg["b"],
                k_static)
        else:
            packed = bm25_essential_topk_batch(
                dp.block_docids, dp.block_tfs, reg["flat_docids"],
                reg["flat_tfs"], sel, ws, dp.doc_lens, masks, mask_ids,
                ne_start, ne_len, ne_idf, ne_bound,
                self._weight_dtype()(dp.avg_len), reg["k1"], reg["b"],
                k_static)
        # ONE device→host sync per cohort, through the tracked funnel
        out = _readback("search.fastpath.essential_cohort", packed)
        took_ms = int((time.time() - t_arrive) * 1000)
        idx_b = reg["index"].encode()
        h = self.front.h
        self.stats["cohorts"] += 1
        self.stats["ess_queries"] = self.stats.get("ess_queries", 0) \
            + len(items)
        bad_set = set(bad)
        if responded is None:
            responded = set()
        refire: list = []
        for qi, (tok, k, term_ids, filt, essd) in enumerate(items):
            if tok in bad_set:
                self._respond_empty(tok, reg)
                responded.add(tok)
                continue
            ok = int(out[qi, 2 * k_static:][0])
            if not ok:
                refire.append((tok, k, term_ids, filt, essd))
                continue
            vals = out[qi, :k_static]
            ids = _unpack_ids(out[qi, k_static:2 * k_static])
            nhit = int(min(k, np.isfinite(vals).sum()))
            v = np.ascontiguousarray(vals[:nhit])
            d = np.ascontiguousarray(ids[:nhit])
            if h is None:
                return
            self.lib.es_fast_respond(
                h, tok, idx_b,
                d.ctypes.data_as(ctypes.c_void_p),
                v.ctypes.data_as(ctypes.c_void_p),
                nhit, essd[5], b"eq", took_ms)
            responded.add(tok)
        self.stats["fast_queries"] += len(items) - len(refire)
        if refire:
            for tok, k, term_ids, filt, _essd in refire:
                if len(reg["ess_bad"]) < 100_000:
                    reg["ess_bad"].add((tuple(term_ids), filt, k))
            self._refire_full(reg, refire, t_arrive, stack,
                              rows)
            for tok, *_ in refire:
                responded.add(tok)

    # ---------------------------------------------------- shared pieces
    #
    # The launch mask stack [F_SLOTS, ND] is PERSISTENT on device: row 0
    # is the plain live mask, rows 1..F-1 are assigned to filter SETS as
    # they first appear and updated in place (`.at[row].set`). The old
    # per-launch jnp.stack of F_SLOTS×ND rows was a ~64 MB device op on
    # EVERY filtered launch — at 2M docs it collapsed the bool lane to
    # ~1 qps in the degraded tunnel. Rows are assigned ONLY on the drain
    # thread (_route_cohort) and the resolved (stack, row map) snapshot
    # rides into each launch, so launch workers never mutate it.

    def _resolve_mask_rows(self, reg, filts):
        """(stack_device, {filt: row}) for a cohort's distinct filter
        sets; unknown-term filters map to row None (match nothing)."""
        from elasticsearch_tpu.ops.fastpath import F_SLOTS
        if reg.get("mask_stack") is None:
            reg["mask_stack"] = reg["plain_masks"]
            reg["stack_map"] = {}
            reg["stack_next"] = 1
        st = reg["mask_stack"]
        smap = reg["stack_map"]
        out: Dict[tuple, Optional[int]] = {}
        for filt in filts:
            if not filt:
                continue
            row = smap.get(filt)
            if row is None:
                col = self._filter_col(reg, filt)
                if col is None:
                    out[filt] = None
                    continue
                # round-robin eviction over rows 1..F-1, but never a
                # row ALREADY RESOLVED for this cohort (evicting one
                # would silently evaluate its queries against the wrong
                # filter column); a cohort holds <= F_SLOTS-1 distinct
                # sets so a free row always exists
                taken = {r for r in out.values() if r is not None}
                taken |= {smap[f] for f in filts
                          if f and f in smap}
                for _ in range(F_SLOTS - 1):
                    row = reg["stack_next"]
                    reg["stack_next"] = 1 + (row % (F_SLOTS - 1))
                    if row not in taken:
                        break
                for old_f, old_r in list(smap.items()):
                    if old_r == row:
                        del smap[old_f]
                st = st.at[row].set(col)
                smap[filt] = row
            out[filt] = row
        reg["mask_stack"] = st
        return st, out

    def _respond_hits(self, reg, tok, v, d, k, total, took_ms,
                      term_ids=None, filt=None):
        """Marshal one query's (contract-ordered) hits back through the
        C++ front; records the exact θ when the result fills k."""
        nhit = len(v)
        if (term_ids is not None and k == self.max_k and nhit == k
                and len(reg["theta"]) < 100_000):
            # exact kth + exact total: licenses the essential lane for
            # this query on this immutable registration
            reg["theta"][(tuple(term_ids), filt, k)] = (
                float(v[-1]), total)
            self.stats["theta_stores"] = \
                self.stats.get("theta_stores", 0) + 1
        h = self.front.h
        if h is None:
            return
        self.lib.es_fast_respond(
            h, tok, reg["index"].encode(),
            d.ctypes.data_as(ctypes.c_void_p),
            v.ctypes.data_as(ctypes.c_void_p),
            nhit, total, b"eq", took_ms)

    def _filter_col(self, reg, filt):
        """Device column: base live AND the filter-set mask (cached; the
        kernel contract is "base live AND filters" — deleted docs must
        never resurface through a filter column). None ⇒ a filter term
        is unknown (the filter matches nothing)."""
        import jax.numpy as jnp
        cached = reg["filter_live"].get(filt)
        if cached is not None:
            return cached
        dp, dev = reg["dp"], reg["dev"]
        pf = dp.host
        terms = []
        for t in filt:
            if not (0 <= t < len(pf.terms)):
                return None
            terms.append((reg["field"], (pf.terms[t],), False))
        mask, _host = dev.composed_filter_mask(terms)
        col = jnp.logical_and(dev.live, mask)
        if len(reg["filter_live"]) < 256:
            reg["filter_live"][filt] = col
        return col

    def _mesh_active(self, reg) -> bool:
        """The ONE gate for replica-sharded v1 cohorts: a mesh bound at
        registration AND the backend still enabled — the
        ESTPU_MESH_SERVING=0 kill switch must reach already-registered
        indices immediately, not at the next re-registration (the
        unsharded signature may cold-compile once; a kill switch is
        allowed that)."""
        return (reg.get("rmesh") is not None
                and self.mesh_backend is not None
                and self.mesh_backend.enabled())

    def _v1_inputs(self, reg, sel, ws, stack, mask_ids):
        """The v1 kernel's launch inputs, replica-sharded over the
        registration's mesh when one is bound: corpus arrays ride as
        replicated handles (cached by identity — the mask stack
        re-replicates only when a filter row actually changed), the
        per-query rows shard P("replica"). ONE compile signature per
        bucket either way (warm and serve both come through here)."""
        dp = reg["dp"]
        rmesh = reg.get("rmesh")
        mb = self.mesh_backend
        if rmesh is None or mb is None or not self._mesh_active(reg):
            return (dp.block_docids, dp.block_tfs, sel, ws,
                    dp.doc_lens, stack, mask_ids)
        return (mb.replicated(rmesh, dp.block_docids),
                mb.replicated(rmesh, dp.block_tfs),
                mb.shard_rows(rmesh, sel),
                mb.shard_rows(rmesh, ws),
                mb.replicated(rmesh, dp.doc_lens),
                mb.replicated(rmesh, stack),
                mb.shard_rows(rmesh, mask_ids))

    def _launch_group_inner(self, reg, bucket, items, t_arrive,
                            stack, rows):
        from elasticsearch_tpu.ops.fastpath import bm25_topk_total_batch
        dp = reg["dp"]
        q = len(items)
        sel = np.full((self.q_batch, bucket), dp.zero_block,
                      np.int32)
        ws = np.zeros((self.q_batch, bucket), self._weight_dtype())
        mask_ids = np.zeros(self.q_batch, np.int32)
        starts, nbs, idf = reg["starts"], reg["nb"], reg["idf"]
        no_match: list = []
        for qi, (tok, k, term_ids, filt) in enumerate(items):
            pos = 0
            for t in term_ids:
                if t < 0:
                    continue
                cnt = int(nbs[t])
                s = int(starts[t])
                sel[qi, pos:pos + cnt] = np.arange(s, s + cnt,
                                                   dtype=np.int32)
                ws[qi, pos:pos + cnt] = idf[t]
                pos += cnt
            if filt:
                row = rows.get(filt)
                if row is None:          # unknown filter term ⇒ no hits
                    no_match.append(tok)
                    sel[qi, :] = dp.zero_block
                    ws[qi, :] = 0.0
                    continue
                mask_ids[qi] = row
        k_static = self.max_k
        bd, bt, sel_m, ws_m, dl, mk, mi = self._v1_inputs(
            reg, sel, ws, stack, mask_ids)
        packed = bm25_topk_total_batch(
            bd, bt, sel_m, ws_m, dl, mk, mi,
            self._weight_dtype()(dp.avg_len), reg["k1"], reg["b"],
            k_static)
        # ONE device→host sync per cohort, through the tracked funnel
        out = _readback("search.fastpath.v1_cohort", packed)
        took_ms = int((time.time() - t_arrive) * 1000)
        self.stats["cohorts"] += 1
        if self._mesh_active(reg):
            self.stats["mesh_cohorts"] = \
                self.stats.get("mesh_cohorts", 0) + 1
            self.mesh_backend._dispatch("replica", q)
        self.stats["fast_queries"] += q
        no_match_set = set(no_match)
        for qi, (tok, k, term_ids, filt) in enumerate(items):
            if tok in no_match_set:
                self._respond_empty(tok, reg)
                continue
            vals = out[qi, :k_static]
            ids = _unpack_ids(out[qi, k_static:2 * k_static])
            total = int(out[qi, 2 * k_static:][0])
            nhit = int(min(k, np.isfinite(vals).sum()))
            v = vals[:nhit]
            d = ids[:nhit]
            # ES tie order: equal scores rank by docid ascending (the
            # device top_k's tie order is arbitrary)
            order = np.lexsort((d, -v))
            self._respond_hits(reg, tok, np.ascontiguousarray(v[order]),
                               np.ascontiguousarray(d[order]),
                               k, total, took_ms, term_ids, filt)
