"""Script engine: a sandboxed expression language compiled to batched jnp ops.

The Painless analogue (ref: modules/lang-painless — ANTLR→AST→bytecode with
per-context allowlists; and the vector script functions in
x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:112-170). Where
Painless compiles to JVM bytecode run per document, this engine parses the
expression with Python's ``ast`` module against a strict node allowlist and
evaluates it ONCE over whole device arrays — ``doc['f'].value`` is a
[n_docs] column, ``cosineSimilarity(...)`` a matmul — so a script_score is
a fused XLA computation, not a per-doc interpreter loop.

Supported surface (the score-script context):
- arithmetic / comparisons / boolean ops, parentheses
- ``doc['field'].value`` — numeric doc values column
- ``_score`` — the subquery's BM25 score column
- ``params.name`` / ``params['name']`` — request parameters
- ``cosineSimilarity(params.qv, 'field')``, ``dotProduct(...)``,
  ``l2norm(...)`` — dense-vector functions (return per-doc columns)
- ``Math.log/log10/sqrt/exp/abs/min/max/pow/floor/ceil``, ``saturation``,
  ``sigmoid``, ``rank_feature``-ish helpers

Compilation is cached per source string (ref: ScriptService compilation
cache + rate limits, script/ScriptService.java).
"""

from __future__ import annotations

import ast
import re
import threading
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import ScriptException

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare,
    ast.Call, ast.Attribute, ast.Subscript, ast.Name, ast.Constant,
    ast.Load, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Mod, ast.Pow,
    ast.FloorDiv, ast.USub, ast.UAdd, ast.Not, ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.IfExp,
)


class _Math:
    log = staticmethod(jnp.log)
    log10 = staticmethod(jnp.log10)
    sqrt = staticmethod(jnp.sqrt)
    exp = staticmethod(jnp.exp)
    abs = staticmethod(jnp.abs)
    min = staticmethod(jnp.minimum)
    max = staticmethod(jnp.maximum)
    pow = staticmethod(jnp.power)
    floor = staticmethod(jnp.floor)
    ceil = staticmethod(jnp.ceil)
    E = float(np.e)
    PI = float(np.pi)


class _DocColumn:
    """`doc['field']` — exposes .value / .size() like the painless doc map."""

    def __init__(self, values, missing):
        self.value = values
        self._missing = missing

    def size(self):
        return jnp.where(self._missing, 0, 1)

    @property
    def empty(self):
        return self._missing


class _Params:
    def __init__(self, params: Dict[str, Any]):
        self._params = params

    def __getattr__(self, name):
        try:
            return self._params[name]
        except KeyError:
            raise ScriptException(f"missing script parameter [{name}]")

    def __getitem__(self, name):
        return getattr(self, name)


class ScriptContext:
    """Everything a score script may touch, columnar (built by the query
    layer per segment)."""

    def __init__(self, doc_columns: Callable[[str], _DocColumn],
                 params: Dict[str, Any],
                 score=None,
                 vector_fns: Dict[str, Callable] = None,
                 mask=None):
        self._doc_columns = doc_columns
        self.params = _Params(params)
        self.score = score
        self.vector_fns = vector_fns or {}
        # matched-doc mask — the statement-script path iterates THIS,
        # not score>0 (filter-only subqueries match with score 0)
        self.mask = mask


class _Doc:
    def __init__(self, ctx: ScriptContext):
        self._ctx = ctx

    def __getitem__(self, field: str) -> _DocColumn:
        return self._ctx._doc_columns(field)


# the per-context allowlist (ref: painless per-context whitelists)
_ALLOWED_NAMES = {
    "doc", "params", "_score", "Math", "saturation", "sigmoid",
    "cosineSimilarity", "dotProduct", "l2norm", "True", "False",
}


def _validate(tree: ast.AST, source: str):
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ScriptException(
                f"compile error: [{type(node).__name__}] is not allowed in "
                f"scripts: [{source}]")
        if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
            raise ScriptException(
                f"compile error: access to [{node.attr}] is not allowed")
        if isinstance(node, ast.Name) and node.id not in _ALLOWED_NAMES:
            raise ScriptException(
                f"compile error: unknown variable [{node.id}]")


_cache: Dict[str, Any] = {}
_cache_lock = threading.Lock()


# per-doc interpretation cap: statement scripts (loops, locals) can't be
# vectorized onto the device, so they run the sandboxed interpreter doc
# by doc — O(n_docs) host time. Above this, demand the expression form
# (which compiles to one fused XLA computation) instead of silently
# burning minutes of host CPU.
SCRIPT_INTERP_MAX_DOCS = 200_000


_DECL_RE = re.compile(
    r"^(def|double|float)\s+(\w+)\s*=(?!=)\s*(.+)$", re.S)
_ASSIGN_RE = re.compile(r"^()(\w+)\s*=(?!=)\s*(.+)$", re.S)
# int/int division truncates in painless (Java semantics) but not in
# the folded float evaluation — any literal-by-literal / or % bails
_INT_DIV_RE = re.compile(r"(?<![\w.])\d+\s*[/%]\s*\d+(?![\w.])")


def _desugar_straightline(source: str) -> Optional[str]:
    """Fold a straight-line statement script — local declarations /
    reassignments followed by ``return expr`` — into ONE expression by
    symbolic substitution, so it rides the vectorized tier instead of
    the per-doc interpreter (XLA CSEs any duplicated subexpressions).
    Returns None when the script has control flow, strings, or any
    statement shape the fold can't prove safe — those keep the full
    interpreter semantics."""
    # string literals (doc['field'] keys) are masked behind \x00N\x00
    # placeholders so ';' splitting and \b-substitution can never touch
    # their contents, then restored into the folded expression
    lits: List[str] = []

    def _mask(m):
        lits.append(m.group(0))
        return f"\x00{len(lits) - 1}\x00"

    masked = re.sub(r"'[^'\n]*'|\"[^\"\n]*\"", _mask, source)
    if "'" in masked or '"' in masked:
        return None           # unterminated / escaped quoting: bail
    if _INT_DIV_RE.search(masked):
        return None           # Java int division truncates; float won't
    stmts = [s.strip() for s in masked.split(";") if s.strip()]
    if not stmts or not re.match(r"return\b", stmts[-1]):
        return None
    env: Dict[str, str] = {}

    def subst(expr: str) -> str:
        for name, rep in env.items():
            expr = re.sub(rf"\b{re.escape(name)}\b", rep, expr)
        return expr

    has_div = re.search(r"[/%]", masked) is not None
    for s in stmts[:-1]:
        m = _DECL_RE.match(s) or _ASSIGN_RE.match(s)
        if m is None:
            return None
        typ, name, expr = m.group(1), m.group(2), m.group(3)
        if typ == "def" and has_div:
            return None       # a def local could be int-typed: / or %
        env[name] = "(" + subst(expr) + ")"
    ret = stmts[-1][len("return"):].strip()
    if not ret:
        return None
    return re.sub(r"\x00(\d+)\x00", lambda m: lits[int(m.group(1))],
                  subst(ret))


def compile_script(source: str):
    """Parse + validate; returns a callable(ctx) -> array.

    Two tiers (the TPU-first inversion of Painless's per-doc bytecode):
    1. expression scripts — including straight-line statement scripts
       folded by :func:`_desugar_straightline` — compile to COLUMNAR
       jnp: one fused XLA computation over whole device arrays;
    2. statement scripts with control flow (if/for/while, functions —
       anything the expression grammar rejects) compile to the full
       Painless interpreter (script/) and evaluate per matched doc on
       host.
    """
    with _cache_lock:
        code = _cache.get(source)
    if code is None:
        expr_src = source
        try:
            tree = ast.parse(expr_src, mode="eval")
            _validate(tree, expr_src)
        except (SyntaxError, ScriptException):
            folded = _desugar_straightline(source)
            if folded is None:
                return _compile_painless_score(source)
            try:
                expr_src = folded
                tree = ast.parse(expr_src, mode="eval")
                _validate(tree, expr_src)
            except (SyntaxError, ScriptException):
                return _compile_painless_score(source)
        code = compile(tree, "<script>", "eval")
        with _cache_lock:
            _cache[source] = code

    def run(ctx: ScriptContext):
        namespace = {
            "doc": _Doc(ctx),
            "params": ctx.params,
            "_score": ctx.score,
            "Math": _Math,
            "saturation": lambda v, pivot: v / (v + pivot),
            "sigmoid": lambda v, k, a: v ** a / (k ** a + v ** a),
        }
        namespace.update(ctx.vector_fns)
        try:
            return eval(code, {"__builtins__": {}}, namespace)  # noqa: S307
        except ScriptException:
            raise
        except Exception as e:
            raise ScriptException(f"runtime error: {e} in script [{source}]")

    run.vectorized = True      # expression tier: one fused computation
    return run


def _compile_painless_score(source: str):
    """Statement-script score path: parse with the full Painless
    compiler now (errors surface at query parse, like the reference's
    compile-on-PUT), evaluate per matched doc at run time."""
    from elasticsearch_tpu.script.interp import (ContextShim,
                                                 PainlessError,
                                                 compile_painless)
    try:
        script = compile_painless(source)
    except PainlessError as e:
        raise ScriptException(str(e))
    except ScriptException:
        raise
    except Exception as e:
        raise ScriptException(f"compile error: {e}: [{source}]")

    class _DocShim(ContextShim):
        def __init__(self, cols, i):
            self._cols = cols
            self._i = i

        def pl_index(self, field):
            vals, miss = self._cols(field)
            v = vals[self._i]
            # numpy scalars → plain Python numbers (the interpreter's
            # type checks and Java semantics key on int/float)
            return _PlCol(v.item() if hasattr(v, "item") else v,
                          bool(miss[self._i]))

        def pl_call(self, name, args):
            if name == "containsKey":
                try:
                    self._cols(args[0])
                    return True
                except Exception:
                    return False
            raise PainlessError(f"unknown method [{name}] on doc")

    class _PlCol(ContextShim):
        def __init__(self, value, missing):
            self._value = value
            self._missing = missing

        def pl_get(self, name):
            if name == "value":
                if self._missing:
                    raise PainlessError(
                        "A document doesn't have a value for a field")
                return self._value
            if name == "empty":
                return self._missing
            raise PainlessError(f"unknown field [{name}]")

        def pl_call(self, name, args):
            if name == "size":
                return 0 if self._missing else 1
            if name == "getValue":
                return self.pl_get("value")
            raise PainlessError(f"unknown method [{name}]")

    def run(ctx: ScriptContext):
        import numpy as _np

        col_cache: Dict[str, tuple] = {}

        def cols(field):
            hit = col_cache.get(field)
            if hit is None:
                c = ctx._doc_columns(field)
                hit = (_np.asarray(c.value), _np.asarray(c._missing))
                col_cache[field] = hit
            return hit

        score_np = (_np.asarray(ctx.score)
                    if ctx.score is not None else None)
        mask_np = (_np.asarray(ctx.mask)
                   if ctx.mask is not None else None)
        nd = (len(score_np) if score_np is not None
              else (len(mask_np) if mask_np is not None else None))
        if nd is None:
            # probe any referenced field for the doc count
            raise ScriptException(
                "statement scripts require a scored context")
        if nd > SCRIPT_INTERP_MAX_DOCS:
            raise ScriptException(
                f"statement script over {nd} docs exceeds the "
                f"interpreter budget ({SCRIPT_INTERP_MAX_DOCS}); "
                f"use the expression form (vectorized) instead")
        params = dict(ctx.params._params)
        out = _np.zeros(nd, _np.float32)
        # iterate the MATCHED docs: the mask when available (filter-only
        # subqueries match with base score 0), else score > 0
        if mask_np is not None:
            idxs = _np.nonzero(mask_np)[0]
        elif score_np is not None:
            idxs = _np.nonzero(score_np > 0)[0]
        else:
            idxs = range(nd)
        for i in idxs:
            env = {"doc": _DocShim(cols, int(i)),
                   "params": params,
                   "_score": (float(score_np[i])
                              if score_np is not None else 0.0)}
            try:
                v = script.execute(env)
            except PainlessError as e:
                raise ScriptException(str(e))
            out[i] = float(v) if v is not None else 0.0
        return jnp.asarray(out)

    run.vectorized = False     # per-doc interpreter tier
    return run


class StoredScripts:
    """Cluster-stored scripts/templates (ref: PUT /_scripts/{id} →
    StoredScriptSource kept in cluster state; script/ScriptMetadata).
    Persisted to a JSON file under the node data path."""

    def __init__(self, data_path: str):
        import json as _json
        import os as _os
        self._path = _os.path.join(data_path, "_scripts.json")
        self._scripts: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        if _os.path.exists(self._path):
            with open(self._path) as fh:
                self._scripts = _json.load(fh)

    def _persist_locked(self):
        import json as _json
        import os as _os
        tmp = self._path + ".tmp"
        with open(tmp, "w") as fh:
            _json.dump(self._scripts, fh)
        _os.replace(tmp, self._path)

    def put(self, script_id: str, script: Dict[str, Any]) -> None:
        if not isinstance(script, dict) or "source" not in script:
            raise ScriptException("stored script requires [script.source]")
        with self._lock:
            self._scripts[script_id] = {
                "lang": script.get("lang", "painless"),
                "source": script["source"],
            }
            self._persist_locked()

    def get(self, script_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._scripts.get(script_id)

    def delete(self, script_id: str) -> bool:
        with self._lock:
            if script_id in self._scripts:
                del self._scripts[script_id]
                self._persist_locked()
                return True
            return False
