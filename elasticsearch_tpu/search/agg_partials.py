"""Mergeable aggregation partials: the distributed-aggs wire contract.

The reference makes every agg result an ``InternalAggregation`` that
serializes, merges associatively, and finalizes on the coordinator
(ref: InternalAggregations.java / the per-type ``reduce()`` tree,
consumed incrementally by QueryPhaseResultConsumer.java — PAPER.md
layer 7). This module is that contract for the TPU engine's columnar
aggs: each shard runs the same mask-algebra collectors as the
single-node path (search/aggregations.py — device kernels included)
but stops at the MERGEABLE MOMENTS instead of the finished response:

- simple numeric metrics travel as ``(count, sum, min, max, sum_sq)``
  moments — additive, so merge order only moves float rounding;
- the percentile family (percentiles / percentile_ranks / boxplot /
  median_absolute_deviation) travels as a bounded TDigest sketch
  (search/sketches.py — exact below the centroid budget, documented
  error above it). NO raw-sample carrier ever crosses the wire;
- bucket aggs travel as key→{count, sub-partials} maps and merge by
  key, recursing through sub-aggregation trees;
- composite pages stay exact across shards: each shard reports its
  first ``size`` keys after ``after`` plus a truncation bound, and the
  final reduce never emits a key past the smallest truncated shard's
  last key (a key beyond it could be undercounted);
- pipeline aggs (sibling AND parent) never cross the wire — they are
  pure functions of finalized buckets and run once on the coordinator.

Three pure functions define the protocol — ``collect_partials`` (data
node), ``merge_partials`` (associative pairwise reduce), and
``finalize_partials`` (coordinator) — plus ``AggReduceConsumer``, the
QueryPhaseResultConsumer analogue: it buffers shard partials, reduces
every ``batched_reduce_size`` arrivals (coordinator memory holds at
most one batch + one accumulator), charges buffered bytes to the
``request`` breaker, and feeds the ``search.agg_reduce.*`` metrics.

Aggregation types outside ``DISTRIBUTED_METRICS`` /
``DISTRIBUTED_BUCKETS`` raise a typed (non-retryable) error on the
distributed path before any shard fan-out; the single-node path still
serves them all. See COMPONENTS.md "Distributed aggregations".
"""

from __future__ import annotations

import copy
import json
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.search import aggregations as A
from elasticsearch_tpu.search.sketches import TDigest
from elasticsearch_tpu.utils.breaker import payload_size_bytes

# ---------------------------------------------------------------------------
# supported surface
# ---------------------------------------------------------------------------

MOMENT_METRICS = {"sum", "min", "max", "avg", "value_count", "stats",
                  "extended_stats"}
DIGEST_METRICS = {"percentiles", "percentile_ranks", "boxplot",
                  "median_absolute_deviation"}
DISTRIBUTED_METRICS = (MOMENT_METRICS | DIGEST_METRICS
                       | {"cardinality", "weighted_avg", "top_hits",
                          "scripted_metric"})
DISTRIBUTED_BUCKETS = {"terms", "rare_terms", "histogram",
                       "date_histogram", "range", "date_range",
                       "filter", "filters", "missing", "global",
                       "composite"}

# ES defaults batched_reduce_size to 512; shard counts in this engine
# are small, so a low default keeps the incremental reduce actually
# incremental (and its metrics observable) on real clusters
DEFAULT_BATCHED_REDUCE_SIZE = 5


def check_distributed_support(spec: Dict[str, Any]) -> None:
    """Reject agg trees the distributed path cannot merge — typed
    (illegal_argument → non-retryable) BEFORE any shard fan-out, so the
    coordinator never burns a fan-out on a request that cannot reduce."""
    for name, node in (spec or {}).items():
        if not isinstance(node, dict):
            raise ParsingException(
                f"[{name}] is not an aggregation object")
        types = [k for k in node
                 if k not in ("aggs", "aggregations", "meta")]
        if len(types) != 1:
            raise ParsingException(
                f"Expected exactly one aggregation type under [{name}], "
                f"got {types}")
        t = types[0]
        if t in A.PARENT_PIPELINES or t in A.PIPELINE_AGGS:
            continue            # pure coordinator-side functions
        if t not in DISTRIBUTED_METRICS | DISTRIBUTED_BUCKETS:
            raise IllegalArgumentException(
                f"aggregation [{name}] of type [{t}] is not supported "
                "on the distributed search path yet (single-node "
                "search serves it; see COMPONENTS.md \"Distributed "
                "aggregations\" for the supported set)")
        sub = node.get("aggs", node.get("aggregations"))
        if sub:
            if t in DISTRIBUTED_METRICS:
                raise ParsingException(
                    f"metric aggregation [{name}] cannot hold "
                    "sub-aggregations")
            check_distributed_support(sub)


# ---------------------------------------------------------------------------
# collect (data-node side)
# ---------------------------------------------------------------------------

def collect_partials(spec: Dict[str, Any], ctx, mapper,
                     device_cache=None) -> Dict[str, Any]:
    """One shard's partial tree for an aggs spec — JSON-serializable,
    bounded (moments / sketches / trimmed bucket maps), mergeable via
    ``merge_partials``. The device cache scopes exactly like
    ``compute_aggs`` so the shared collectors (terms ord-major counts,
    fused metric moments, histogram scatter-add) ride the device at
    scale."""
    token = A._DEVICE_CACHE.set(device_cache)
    try:
        return _collect_level(spec, ctx, mapper)
    finally:
        A._DEVICE_CACHE.reset(token)


def _collect_level(spec, ctx, mapper) -> Dict[str, Any]:
    out = {}
    for name, node in (spec or {}).items():
        agg_type, body, sub = A._split_node(name, node)
        if agg_type in A.PIPELINE_AGGS or agg_type in A.PARENT_PIPELINES:
            continue                      # coordinator-side
        out[name] = _collect_one(agg_type, body, sub, ctx, mapper)
    return out


def _regular_sub(sub):
    return A._split_parent_pipelines(sub)[0] if sub else {}


def _collect_one(agg_type, body, sub, ctx, mapper):
    if agg_type in MOMENT_METRICS:
        return _collect_moments(body, ctx, agg_type)
    if agg_type in DIGEST_METRICS:
        values = _metric_values(ctx, body)
        return {"d": TDigest.from_values(
            values, A._digest_compression(body)).to_wire()}
    if agg_type == "cardinality":
        return _collect_cardinality(body, ctx)
    if agg_type == "weighted_avg":
        return _collect_weighted_avg(body, ctx)
    if agg_type == "top_hits":
        return _collect_top_hits(body, ctx, mapper)
    if agg_type == "scripted_metric":
        return {"states": A.scripted_metric_states(body, ctx)}
    if agg_type in ("terms", "rare_terms"):
        return _collect_terms(agg_type, body, sub, ctx, mapper)
    if agg_type in ("histogram", "date_histogram"):
        return _collect_histogram(agg_type, body, sub, ctx, mapper)
    if agg_type in ("range", "date_range"):
        return _collect_range(agg_type, body, sub, ctx, mapper)
    if agg_type == "filter":
        from elasticsearch_tpu.search.queries import parse_query
        bucket_ctx = A._refine(
            ctx, A._query_masks(parse_query(body), ctx, mapper))
        return _bucket_partial(bucket_ctx, sub, mapper)
    if agg_type == "filters":
        from elasticsearch_tpu.search.queries import parse_query
        out = {}
        for fname, fspec in (body.get("filters") or {}).items():
            bucket_ctx = A._refine(
                ctx, A._query_masks(parse_query(fspec), ctx, mapper))
            out[fname] = _bucket_partial(bucket_ctx, sub, mapper)
        return {"b": out}
    if agg_type == "missing":
        field = body.get("field")
        submasks = []
        for seg, mask, _m in ctx:
            present = np.zeros(seg.n_docs, bool)
            nv = seg.numerics.get(field)
            if nv is not None:
                present |= ~nv.missing
            kv = seg.keywords.get(field)
            if kv is not None:
                present |= (kv.offsets[1:] - kv.offsets[:-1]) > 0
            pf = seg.postings.get(field)
            if pf is not None:
                present |= pf.field_lengths > 0
            submasks.append(~present)
        return _bucket_partial(A._refine(ctx, submasks), sub, mapper)
    if agg_type == "global":
        global_ctx = [(seg, seg.live.copy(), m)
                      for seg, _msk, m in ctx]
        return _bucket_partial(global_ctx, sub, mapper)
    if agg_type == "composite":
        return _collect_composite(body, sub, ctx, mapper)
    raise IllegalArgumentException(
        f"unhandled distributed agg [{agg_type}]")


def _bucket_partial(bucket_ctx, sub, mapper):
    """{doc_count, sub partials} for one single-bucket agg."""
    out = {"c": sum(int(msk.sum()) for _, msk, _m in bucket_ctx)}
    reg = _regular_sub(sub)
    if reg:
        out["sub"] = _collect_level(reg, bucket_ctx, mapper)
    return out


def _metric_values(ctx, body) -> np.ndarray:
    """The value source of a numeric metric, honoring ``missing``
    (mirrors the host branch of aggregations._metric)."""
    field = body.get("field")
    values = A._numeric_values(ctx, field)
    missing_val = body.get("missing")
    if missing_val is not None:
        n_missing = 0
        for seg, mask, _m in ctx:
            nv = seg.numerics.get(field)
            miss = (nv.missing if nv is not None
                    else np.ones(seg.n_docs, bool))
            n_missing += int((mask[: seg.n_docs] & miss).sum())
        values = np.concatenate(
            [values, np.full(n_missing, float(missing_val))])
    return values


def _collect_moments(body, ctx, agg_type=None):
    """(count, sum, min, max, sum_sq) — via ONE fused device launch per
    segment at scale (ops/aggs.py masked_metric_stats), host numpy
    otherwise. extended_stats always collects host-side: its variance
    cancels catastrophically in the device f32 sum-of-squares (same
    exclusion as the single-node dispatch)."""
    if body.get("missing") is None and agg_type != "extended_stats":
        dev = A._device_metric_stats(ctx, body.get("field"))
        if dev is not None:
            n, s, mn, mx, ss = dev
            return {"n": n, "s": s, "mn": mn, "mx": mx, "ss": ss}
    values = _metric_values(ctx, body)
    n = int(len(values))
    if n == 0:
        return {"n": 0, "s": 0.0, "mn": None, "mx": None, "ss": 0.0}
    return {"n": n, "s": float(values.sum()),
            "mn": float(values.min()), "mx": float(values.max()),
            "ss": float((values ** 2).sum())}


def _collect_cardinality(body, ctx):
    """Exact distinct values (the engine's cardinality is exact —
    memory is O(distinct) per shard, documented)."""
    field = body.get("field")
    distinct: set = set()
    for seg, mask, _m in ctx:
        kv = seg.keywords.get(field)
        if kv is not None:
            bc = A._masked_ord_counts(kv, mask, seg.n_docs)
            distinct.update(kv.terms[int(o)] for o in np.nonzero(bc)[0])
            continue
        nv = seg.numerics.get(field)
        if nv is not None:
            m = mask[: seg.n_docs] & ~nv.missing
            distinct.update(float(v)
                            for v in np.unique(nv.values[m]).tolist())
    return {"vals": sorted(distinct, key=lambda v: (isinstance(v, str),
                                                    v))}


def _collect_weighted_avg(body, ctx):
    vfield = (body.get("value") or {}).get("field")
    wfield = (body.get("weight") or {}).get("field")
    num = den = 0.0
    for seg, mask, _m in ctx:
        vv, vm = A._first_values_and_mask(seg, mask, vfield)
        wv, wm = A._first_values_and_mask(seg, mask, wfield)
        if vv is None or wv is None:
            continue
        m = vm & wm
        num += float((vv[m] * wv[m]).sum())
        den += float(wv[m].sum())
    return {"num": num, "den": den}


def _collect_top_hits(body, ctx, mapper):
    """The shard's finished top-N plus merge keys: sorted top_hits
    merge exactly (the RAW sort value travels with each hit — kept
    untyped so non-numeric sort values merge too); unsorted hits keep
    shard-arrival order like the reference."""
    result = A._metric("top_hits", body, ctx, mapper)
    hits = result["hits"]["hits"]
    keys = []
    if body.get("sort"):
        for h in hits:
            sv = (h.get("sort") or [None])[0]
            keys.append([1, None] if sv is None else [0, sv])
    return {"total": result["hits"]["total"]["value"],
            "hits": hits, "keys": keys}


def _terms_counts(body, ctx) -> Tuple[Dict[Any, int], bool]:
    """(term → count, numeric?) over keyword or numeric doc values —
    the same sources the single-node terms agg reads (device ord-major
    counts at scale)."""
    field = body.get("field")
    counts = A._keyword_terms_counts(ctx, field)
    if counts:
        return counts, False
    ncounts: Dict[float, int] = {}
    for seg, mask, _m in ctx:
        nv = seg.numerics.get(field)
        if nv is None:
            continue
        m = mask[: seg.n_docs] & ~nv.missing
        vals, cnts = np.unique(nv.values[m], return_counts=True)
        for v, c in zip(vals, cnts):
            ncounts[float(v)] = ncounts.get(float(v), 0) + int(c)
    # an empty shard must not claim the field numeric — the flag ORs
    # across shards at merge and would mis-key another shard's keywords
    return ncounts, bool(ncounts)


def _term_submasks(ctx, field, term, numeric):
    if numeric:
        out = []
        for seg, _m2, _m3 in ctx:
            nv = seg.numerics.get(field)
            out.append(np.zeros(seg.n_docs, bool) if nv is None
                       else (~nv.missing & (nv.values == term)))
        return out
    return [A._keyword_membership_mask(seg, field, term)
            for seg, _m2, _m3 in ctx]


def _collect_terms(agg_type, body, sub, ctx, mapper):
    """Terms partial: full count map by default (merge is then EXACT —
    memory O(shard distinct terms), like the single-node collector);
    an explicit ``shard_size`` trims to the shard's top counts with ES
    error accounting (``err`` = the largest dropped count, summed into
    doc_count_error_upper_bound at reduce)."""
    field = body.get("field")
    counts, numeric = _terms_counts(body, ctx)
    # trim (when asked) in the REQUESTED order — a _key-ordered terms
    # agg trimmed by count would drop exactly the buckets the final
    # sort wants (ES trims shard-side in request order for the same
    # reason); the count-error bound only means anything under _count
    order = body.get("order", {"_count": "desc"})
    (order_key, order_dir), = (order.items() if isinstance(order, dict)
                               else [("_count", "desc")])
    if order_key == "_key" and not numeric:
        items = sorted(counts.items(), key=lambda kv: kv[0],
                       reverse=(order_dir == "desc"))
    else:
        items = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    other = 0
    err = 0
    shard_size = body.get("shard_size")
    if agg_type == "terms" and shard_size is not None:
        shard_size = int(shard_size)
        dropped = items[shard_size:]
        items = items[:shard_size]
        other = sum(c for _, c in dropped)
        if not dropped:
            err = 0
        elif order_key == "_count":
            err = max(c for _, c in dropped)
        else:
            # ES convention: the count-error bound is unknowable when
            # the trim order isn't _count — report -1, never a false 0
            err = -1
    reg = _regular_sub(sub)
    terms_out = {}
    for term, c in items:
        entry = {"c": c}
        if reg:
            bucket_ctx = A._refine(
                ctx, _term_submasks(ctx, field, term, numeric))
            entry["sub"] = _collect_level(reg, bucket_ctx, mapper)
        terms_out[str(term)] = entry
    return {"numeric": numeric, "terms": terms_out,
            "other": other, "err": err}


def _histogram_params(agg_type, body):
    """(step_of, key_of, calendar?) — the one step/key convention
    (shared with the single-node branch semantics)."""
    cal_unit = (A._calendar_unit(body) if agg_type == "date_histogram"
                else None)
    if agg_type == "histogram":
        interval = float(body["interval"])
    elif cal_unit is None:
        interval = A._date_interval_ms(body)
    if cal_unit is not None:
        def step_of(vv):
            return A._calendar_floor_ms(vv, cal_unit).astype(np.int64)

        def key_of(step):
            return float(step)
        return step_of, key_of, cal_unit

    def step_of(vv):
        # NaN slots (missing values) are masked out by every caller —
        # zero them first so the int cast never sees an invalid value
        return np.floor(np.nan_to_num(vv) / interval).astype(np.int64)

    def key_of(step):
        return step * interval
    return step_of, key_of, None


def _collect_histogram(agg_type, body, sub, ctx, mapper):
    """step → {count, sub partials}; gap fill and min_doc_count apply
    at FINALIZE (they need the global step range). Fixed intervals with
    metric-only sub-aggs ride the fused device scatter-add columns."""
    field = body.get("field")
    step_of, _key_of, cal_unit = _histogram_params(agg_type, body)
    reg = _regular_sub(sub)
    if cal_unit is None:
        sub_metrics = A._device_histogram_submetrics(reg)
        if sub_metrics is not None:
            interval = (float(body["interval"])
                        if agg_type == "histogram"
                        else A._date_interval_ms(body))
            moments = A._device_histogram_moments(
                ctx, field, interval, sub_metrics)
            if moments is not None:
                lo, counts, mcols = moments
                out = {}
                for i in range(len(counts)):
                    c = int(counts[i])
                    if c == 0:
                        continue
                    entry = {"c": c}
                    if sub_metrics:
                        entry["sub"] = {
                            name: {"n": int(mcols[name][0][i]),
                                   "s": float(mcols[name][1][i]),
                                   "mn": (float(mcols[name][2][i])
                                          if mcols[name][0][i] else None),
                                   "mx": (float(mcols[name][3][i])
                                          if mcols[name][0][i] else None),
                                   "ss": float(mcols[name][4][i])}
                            for name, _t, _f in sub_metrics}
                    out[str(int(lo + i))] = entry
                return {"b": out}
    # one pass per segment: values, mask, and step ids extracted ONCE
    # (the per-step sub-agg refinement below reuses them — recomputing
    # per (step, segment) would be O(buckets × docs))
    seg_cols = []
    step_counts: Dict[int, int] = {}
    for seg, mask, _m in ctx:
        vv, m = A._first_values_and_mask(seg, mask, field)
        if vv is None:
            seg_cols.append((seg, None, None))
            continue
        steps = step_of(vv)
        seg_cols.append((seg, m, steps))
        uniq, cnts = np.unique(steps[m], return_counts=True)
        for u, c in zip(uniq, cnts):
            step_counts[int(u)] = step_counts.get(int(u), 0) + int(c)
    out = {}
    for step, c in step_counts.items():
        entry = {"c": c}
        if reg:
            submasks = [
                (np.zeros(seg.n_docs, bool) if m is None
                 else (m & (steps == step)))
                for seg, m, steps in seg_cols]
            entry["sub"] = _collect_level(
                reg, A._refine(ctx, submasks), mapper)
        out[str(step)] = entry
    return {"b": out}


def _collect_range(agg_type, body, sub, ctx, mapper):
    """Positional range buckets: bounds resolve shard-side (date math,
    mapper formats) and travel in ``meta`` — merge is positional."""
    field = body.get("field")
    reg = _regular_sub(sub)
    if agg_type == "date_range":
        # reuse the single-node bound parser via a tiny spec evaluation:
        # compute bounds once with the shard's mapper
        metas, bounds = _date_range_bounds(body, mapper)
    else:
        metas, bounds = [], []
        for r in body.get("ranges", []):
            frm, to = r.get("from"), r.get("to")
            key = r.get("key", f"{frm if frm is not None else '*'}-"
                               f"{to if to is not None else '*'}")
            meta = {"key": key}
            if frm is not None:
                meta["from"] = float(frm)
            if to is not None:
                meta["to"] = float(to)
            metas.append(meta)
            bounds.append((float(frm) if frm is not None else None,
                           float(to) if to is not None else None))
    buckets = []
    for frm, to in bounds:
        submasks = []
        for seg, mask, _m in ctx:
            vv, m = A._first_values_and_mask(seg, mask, field)
            if vv is None:
                submasks.append(np.zeros(seg.n_docs, bool))
                continue
            in_r = m.copy()
            if frm is not None:
                in_r &= vv >= frm
            if to is not None:
                in_r &= vv < to
            submasks.append(in_r)
        buckets.append(_bucket_partial(
            A._refine(ctx, submasks), sub, mapper))
    return {"b": buckets, "meta": metas}


def _date_range_bounds(body, mapper):
    """date_range bounds + response meta via the single-node parser
    (one no-doc evaluation of the range spec)."""
    out = A._bucket("date_range", {**body, "ranges": body.get(
        "ranges", [])}, {}, [], mapper)
    metas = []
    bounds = []
    for b in out["buckets"]:
        meta = {k: v for k, v in b.items() if k != "doc_count"}
        metas.append(meta)
        bounds.append((meta.get("from"), meta.get("to")))
    return metas, bounds


def _composite_keyjson(key: List[Any]) -> str:
    return json.dumps(key, sort_keys=False, separators=(",", ":"))


def _collect_composite(body, sub, ctx, mapper):
    """The shard's first ``size`` composite keys after ``after`` in
    composite order, plus the truncation flag the exact-paging reduce
    needs (see module docstring)."""
    import functools
    sources = body.get("sources", [])
    if not sources:
        raise ParsingException("composite requires [sources]")
    size = int(body.get("size", 10))
    after = body.get("after")
    names, orders, missing_ok = [], [], []
    for src in sources:
        (name, spec), = src.items()
        (stype, sbody), = spec.items()
        names.append(name)
        orders.append(sbody.get("order", "asc"))
        missing_ok.append(bool(sbody.get("missing_bucket", False)))
    seg_source_vals = []
    for seg, _mask, _m in ctx:
        row = []
        for src in sources:
            (name, spec), = src.items()
            (stype, sbody), = spec.items()
            row.append(A._composite_source_values(stype, sbody, seg))
        seg_source_vals.append(row)
    groups: Dict[tuple, List[List[int]]] = {}
    counts: Dict[tuple, int] = {}
    for si, (seg, mask, _m) in enumerate(ctx):
        docs = np.nonzero(mask[: seg.n_docs])[0]
        for d in docs:
            key = []
            ok = True
            for j in range(len(sources)):
                vals, valid = seg_source_vals[si][j]
                if vals is None or not bool(valid[d]):
                    if missing_ok[j]:
                        key.append(None)
                    else:
                        ok = False
                        break
                else:
                    v = vals[d]
                    key.append(float(v) if isinstance(
                        v, (np.floating, np.integer)) else v)
            if not ok:
                continue
            kt = tuple(key)
            if kt not in groups:
                groups[kt] = [[] for _ in ctx]
                counts[kt] = 0
            groups[kt][si].append(int(d))
            counts[kt] += 1
    keyfn = functools.cmp_to_key(
        lambda a, b: A._composite_cmp(a, b, orders))
    ordered = sorted(groups, key=keyfn)
    if after is not None:
        after_t = tuple(after.get(n) for n in names)
        ordered = [k for k in ordered
                   if A._composite_cmp(k, after_t, orders) > 0]
    more = len(ordered) > size
    page = ordered[:size]
    reg = _regular_sub(sub)
    entries = []
    for kt in page:
        entry = {"k": list(kt), "c": counts[kt]}
        if reg:
            submasks = []
            for si, (seg, _mask, _m) in enumerate(ctx):
                sm = np.zeros(seg.n_docs, bool)
                if groups[kt][si]:
                    sm[groups[kt][si]] = True
                submasks.append(sm)
            entry["sub"] = _collect_level(
                reg, A._refine(ctx, submasks), mapper)
        entries.append(entry)
    return {"b": entries, "more": more}


# ---------------------------------------------------------------------------
# merge (associative pairwise reduce)
# ---------------------------------------------------------------------------

def merge_partials(spec: Dict[str, Any],
                   acc: Optional[Dict[str, Any]],
                   part: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge one shard partial into the accumulator. ``acc=None``
    starts a fresh accumulator (the incoming partial is deep-copied —
    wire payloads on the sim transport may be shared with the sender
    and must stay read-only)."""
    if part is None:
        return acc
    if acc is None:
        return copy.deepcopy(part)
    for name, node in (spec or {}).items():
        agg_type, body, sub = A._split_node(name, node)
        if agg_type in A.PIPELINE_AGGS or agg_type in A.PARENT_PIPELINES:
            continue
        if name not in part:
            continue
        if name not in acc:
            acc[name] = copy.deepcopy(part[name])
            continue
        acc[name] = _merge_one(agg_type, body, sub,
                               acc[name], part[name])
    return acc


def _merge_moments(a, p):
    mns = [v for v in (a.get("mn"), p.get("mn")) if v is not None]
    mxs = [v for v in (a.get("mx"), p.get("mx")) if v is not None]
    return {"n": a["n"] + p["n"], "s": a["s"] + p["s"],
            "mn": min(mns) if mns else None,
            "mx": max(mxs) if mxs else None,
            "ss": a["ss"] + p["ss"]}


def _merge_sub(sub, a_entry, p_entry):
    reg = _regular_sub(sub)
    if not reg:
        return
    a_entry["sub"] = merge_partials(reg, a_entry.get("sub"),
                                    p_entry.get("sub"))


def _merge_one(agg_type, body, sub, a, p):
    if agg_type in MOMENT_METRICS:
        return _merge_moments(a, p)
    if agg_type in DIGEST_METRICS:
        from elasticsearch_tpu.search.sketches import merge_wire_digests
        return {"d": merge_wire_digests(
            [a.get("d"), p.get("d")], A._digest_compression(body))}
    if agg_type == "cardinality":
        vals = set(a.get("vals", ())) | set(p.get("vals", ()))
        return {"vals": sorted(vals, key=lambda v: (isinstance(v, str),
                                                    v))}
    if agg_type == "weighted_avg":
        return {"num": a["num"] + p["num"], "den": a["den"] + p["den"]}
    if agg_type == "scripted_metric":
        return {"states": list(a.get("states", ()))
                + list(p.get("states", ()))}
    if agg_type == "top_hits":
        merged = {"total": a["total"] + p["total"],
                  "hits": list(a["hits"]) + list(p["hits"]),
                  "keys": list(a.get("keys", ()))
                  + list(p.get("keys", ()))}
        # keep the buffer bounded: trim to size on every merge (sorted
        # specs re-sort stably by the carried keys first). Two-phase:
        # present values first (ONE sort field → homogeneous type, so
        # reverse= handles desc without negating — strings included),
        # missing-key hits last, both phases arrival-stable.
        size = int(body.get("size", 3))
        if merged["keys"] and body.get("sort"):
            desc = _top_hits_desc(body)
            idx = range(len(merged["hits"]))
            present = [i for i in idx if merged["keys"][i][0] == 0]
            absent = [i for i in idx if merged["keys"][i][0] != 0]
            present.sort(key=lambda i: merged["keys"][i][1],
                         reverse=desc)
            order = present + absent
            merged["hits"] = [merged["hits"][i] for i in order[:size]]
            merged["keys"] = [merged["keys"][i] for i in order[:size]]
        else:
            merged["hits"] = merged["hits"][:size]
            merged["keys"] = merged["keys"][:size]
        return merged
    if agg_type in ("terms", "rare_terms"):
        a_err, p_err = a.get("err", 0), p.get("err", 0)
        out = {"numeric": a.get("numeric") or p.get("numeric"),
               "terms": a.get("terms", {}),
               "other": a.get("other", 0) + p.get("other", 0),
               # -1 (unknowable, non-_count trim order) poisons the sum
               "err": (-1 if a_err < 0 or p_err < 0
                       else a_err + p_err)}
        for term, entry in p.get("terms", {}).items():
            cur = out["terms"].get(term)
            if cur is None:
                out["terms"][term] = copy.deepcopy(entry)
                continue
            cur["c"] += entry["c"]
            _merge_sub(sub, cur, entry)
        return out
    if agg_type in ("histogram", "date_histogram"):
        out = {"b": a.get("b", {})}
        for step, entry in p.get("b", {}).items():
            cur = out["b"].get(step)
            if cur is None:
                out["b"][step] = copy.deepcopy(entry)
                continue
            cur["c"] += entry["c"]
            _merge_sub(sub, cur, entry)
        return out
    if agg_type in ("range", "date_range"):
        ab, pb = a.get("b", []), p.get("b", [])
        if len(ab) != len(pb):
            raise IllegalArgumentException(
                f"[{agg_type}] partials disagree on bucket count "
                f"({len(ab)} vs {len(pb)})")
        for cur, entry in zip(ab, pb):
            cur["c"] += entry["c"]
            _merge_sub(sub, cur, entry)
        return {"b": ab, "meta": a.get("meta") or p.get("meta")}
    if agg_type in ("filter", "missing", "global"):
        a["c"] += p["c"]
        _merge_sub(sub, a, p)
        return a
    if agg_type == "filters":
        out = a.get("b", {})
        for fname, entry in p.get("b", {}).items():
            cur = out.get(fname)
            if cur is None:
                out[fname] = copy.deepcopy(entry)
                continue
            cur["c"] += entry["c"]
            _merge_sub(sub, cur, entry)
        return {"b": out}
    if agg_type == "composite":
        groups = a.get("groups")
        if groups is None:
            # lift the first partial into accumulator form
            groups = {}
            bounds = []
            _composite_accumulate(groups, bounds, a, sub)
            a = {"groups": groups, "bounds": bounds}
        _composite_accumulate(a["groups"], a["bounds"], p, sub)
        return a
    raise IllegalArgumentException(
        f"unhandled distributed agg merge [{agg_type}]")


def _top_hits_desc(body) -> bool:
    spec = body.get("sort")
    spec = spec[0] if isinstance(spec, list) else spec
    if isinstance(spec, str):
        return False
    (_f, sdir), = spec.items()
    order = (sdir.get("order", "asc") if isinstance(sdir, dict)
             else str(sdir))
    return order == "desc"


def _composite_accumulate(groups, bounds, part, sub):
    entries = part.get("b", [])
    for entry in entries:
        jk = _composite_keyjson(entry["k"])
        cur = groups.get(jk)
        if cur is None:
            groups[jk] = copy.deepcopy(entry)
            continue
        cur["c"] += entry["c"]
        _merge_sub(sub, cur, entry)
    if part.get("more") and entries:
        bounds.append(entries[-1]["k"])


# ---------------------------------------------------------------------------
# finalize (coordinator)
# ---------------------------------------------------------------------------

def finalize_partials(spec: Dict[str, Any],
                      acc: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Reduced partials → the ``aggregations`` response object, with
    sibling + parent pipelines computed here (they are pure functions
    of finalized buckets). Internal carriers (``_set``/``_digest``)
    survive for pipeline consumption — callers strip with
    ``strip_internal``."""
    out: Dict[str, Any] = {}
    pipelines: List[Tuple[str, str, Dict[str, Any]]] = []
    for name, node in (spec or {}).items():
        agg_type, body, sub = A._split_node(name, node)
        if agg_type in A.PIPELINE_AGGS:
            pipelines.append((name, agg_type, body))
            continue
        if agg_type in A.PARENT_PIPELINES:
            continue
        out[name] = _finalize_one(agg_type, body, sub,
                                  (acc or {}).get(name))
    for name, agg_type, body in pipelines:
        out[name] = A._compute_pipeline(agg_type, body, out)
    return out


def strip_internal(out: Dict[str, Any]) -> Dict[str, Any]:
    A._strip_internal(out)
    return out


def _finalize_sub(sub, entry, bucket: Dict[str, Any]) -> None:
    reg = _regular_sub(sub)
    if reg:
        bucket.update(finalize_partials(reg, (entry or {}).get("sub")))


def _finalize_one(agg_type, body, sub, part):
    if agg_type in MOMENT_METRICS:
        part = part or {"n": 0, "s": 0.0, "mn": None, "mx": None,
                        "ss": 0.0}
        return A._shape_metric_from_stats(
            agg_type, (part["n"], part["s"], part["mn"], part["mx"],
                       part["ss"]))
    if agg_type in DIGEST_METRICS:
        digest = TDigest.from_wire((part or {}).get("d"))
        return _finalize_digest_metric(agg_type, body, digest)
    if agg_type == "cardinality":
        vals = set((part or {}).get("vals", ()))
        return {"value": len(vals), "_set": vals}
    if agg_type == "weighted_avg":
        den = (part or {}).get("den", 0.0)
        return {"value": (part["num"] / den) if den else None}
    if agg_type == "scripted_metric":
        return A.scripted_metric_reduce(body,
                                        list((part or {}).get(
                                            "states", ())))
    if agg_type == "top_hits":
        part = part or {"total": 0, "hits": [], "keys": []}
        size = int(body.get("size", 3))
        hits = part["hits"][:size]
        return {"hits": {"total": {"value": part["total"],
                                   "relation": "eq"},
                         "hits": hits}}
    if agg_type == "terms":
        return _finalize_terms(body, sub, part)
    if agg_type == "rare_terms":
        return _finalize_rare_terms(body, sub, part)
    if agg_type in ("histogram", "date_histogram"):
        return _finalize_histogram(agg_type, body, sub, part)
    if agg_type in ("range", "date_range"):
        part = part or {"b": [], "meta": []}
        buckets = []
        for entry, meta in zip(part.get("b", []),
                               part.get("meta", [])):
            b = dict(meta)
            b["doc_count"] = entry["c"]
            _finalize_sub(sub, entry, b)
            buckets.append(b)
        return {"buckets": buckets}
    if agg_type in ("filter", "missing", "global"):
        entry = part or {"c": 0}
        out = {"doc_count": entry["c"]}
        _finalize_sub(sub, entry, out)
        return out
    if agg_type == "filters":
        buckets = {}
        for fname, entry in (part or {}).get("b", {}).items():
            b = {"doc_count": entry["c"]}
            _finalize_sub(sub, entry, b)
            buckets[fname] = b
        return {"buckets": buckets}
    if agg_type == "composite":
        return _finalize_composite(body, sub, part)
    raise IllegalArgumentException(
        f"unhandled distributed agg finalize [{agg_type}]")


def _finalize_digest_metric(agg_type, body, digest: TDigest):
    if agg_type == "percentiles":
        if digest.is_empty():
            # single-node shape for an empty value source: {} values
            # and no sketch carrier (aggregations._metric n==0 branch)
            return {"values": {}}
        percents = body.get("percents", [1, 5, 25, 50, 75, 95, 99])
        return {"values": {str(float(p)): digest.quantile(float(p))
                           for p in percents},
                "_digest": digest}
    if agg_type == "percentile_ranks":
        targets = body.get("values", [])
        if digest.is_empty():
            return {"values": {}}
        return {"values": {str(float(t)): digest.cdf(float(t)) * 100.0
                           for t in targets}}
    if agg_type == "median_absolute_deviation":
        return {"value": digest.mad()}
    return A.shape_boxplot(digest)      # boxplot: the ONE shaping


def _term_key_out(term: str, numeric: bool):
    if not numeric:
        return term
    try:
        v = float(term)
    except ValueError:
        # mixed multi-index mapping: a keyword shard's term merged into
        # a numeric-flagged map stays a string key (single-node keeps
        # keyword semantics in the same situation — never crash)
        return term
    return int(v) if v.is_integer() else v


def _term_sort_key(term: str, numeric: bool):
    if not numeric:
        return term
    try:
        return (0, float(term), "")
    except ValueError:
        return (1, 0.0, term)      # mixed-mapping stragglers sort last


def _finalize_terms(body, sub, part):
    part = part or {"numeric": False, "terms": {}, "other": 0, "err": 0}
    size = int(body.get("size", 10))
    numeric = bool(part.get("numeric"))
    counts = {t: e["c"] for t, e in part.get("terms", {}).items()}
    if numeric:
        items = sorted(counts.items(),
                       key=lambda kv: (-kv[1], _term_sort_key(kv[0],
                                                              True)))
    else:
        order = body.get("order", {"_count": "desc"})
        (order_key, order_dir), = (order.items()
                                   if isinstance(order, dict)
                                   else [("_count", "desc")])
        rev = order_dir == "desc"
        if order_key == "_count":
            items = sorted(counts.items(),
                           key=lambda kv: (-kv[1] if rev else kv[1],
                                           kv[0]))
        else:
            items = sorted(counts.items(), key=lambda kv: kv[0],
                           reverse=rev)
    parents = A._split_parent_pipelines(sub)[1] if sub else {}
    buckets = []
    for term, c in items[:size]:
        b = {"key": _term_key_out(term, numeric), "doc_count": c}
        _finalize_sub(sub, part["terms"][term], b)
        buckets.append(b)
    other = part.get("other", 0) + sum(c for _, c in items[size:])
    A._apply_parent_pipelines(parents, buckets)
    return {"doc_count_error_upper_bound": part.get("err", 0),
            "sum_other_doc_count": other, "buckets": buckets}


def _finalize_rare_terms(body, sub, part):
    part = part or {"numeric": False, "terms": {}}
    max_dc = int(body.get("max_doc_count", 1))
    if not 1 <= max_dc <= 100:
        raise ParsingException("[max_doc_count] must be in [1, 100]")
    numeric = bool(part.get("numeric"))
    rare = sorted(((e["c"], t) for t, e in part.get("terms", {}).items()
                   if e["c"] <= max_dc),
                  key=lambda ct: (ct[0], _term_sort_key(ct[1], numeric)))
    parents = A._split_parent_pipelines(sub)[1] if sub else {}
    buckets = []
    for c, term in rare:
        b = {"key": _term_key_out(term, numeric), "doc_count": c}
        _finalize_sub(sub, part["terms"][term], b)
        buckets.append(b)
    A._apply_parent_pipelines(parents, buckets)
    return {"buckets": buckets}


def _finalize_histogram(agg_type, body, sub, part):
    part = part or {"b": {}}
    _step_of, key_of, cal_unit = _histogram_params(agg_type, body)
    min_doc_count = int(body.get("min_doc_count", 0))
    step_entries = {int(s): e for s, e in part.get("b", {}).items()}
    all_steps = sorted(step_entries)
    if all_steps and body.get("extended_bounds") is None \
            and min_doc_count == 0:
        # gap fill under the SAME bucket cap as the single-node path
        # (aggregations.MAX_HISTOGRAM_BUCKETS): one sparse shard pair
        # must not OOM the coordinator reduce outside any breaker
        if cal_unit is not None:
            filled, cur = [], all_steps[0]
            while cur <= all_steps[-1]:
                filled.append(cur)
                A._check_bucket_cap(len(filled), agg_type)
                cur = A._calendar_next_ms(cur, cal_unit)
            all_steps = filled
        else:
            A._check_bucket_cap(all_steps[-1] - all_steps[0] + 1,
                                agg_type)
            all_steps = list(range(all_steps[0], all_steps[-1] + 1))
    parents = A._split_parent_pipelines(sub)[1] if sub else {}
    buckets = []
    for step in all_steps:
        entry = step_entries.get(step, {"c": 0})
        count = entry["c"]
        if count < min_doc_count:
            continue
        key = key_of(step)
        b = {"key": key}
        if agg_type == "date_histogram":
            b["key_as_string"] = A._ms_to_iso(key)
        b["doc_count"] = count
        _finalize_sub(sub, entry, b)
        buckets.append(b)
    A._apply_parent_pipelines(parents, buckets)
    return {"buckets": buckets}


def _finalize_composite(body, sub, part):
    import functools
    sources = body.get("sources", [])
    size = int(body.get("size", 10))
    names, orders = [], []
    for src in sources:
        (name, spec), = src.items()
        (stype, sbody), = spec.items()
        names.append(name)
        orders.append(sbody.get("order", "asc"))
    if part is None:
        return {"buckets": []}
    if "groups" not in part:
        groups = {}
        bounds: List[List[Any]] = []
        _composite_accumulate(groups, bounds, part, sub)
    else:
        groups, bounds = part["groups"], part["bounds"]

    def cmp(a, b):
        return A._composite_cmp(tuple(a), tuple(b), orders)

    ordered = sorted((e["k"] for e in groups.values()),
                     key=functools.cmp_to_key(cmp))
    # exact paging: never emit a key past the smallest truncated
    # shard's last reported key — it could be undercounted there; the
    # next page (after_key = last emitted) will see it whole
    if bounds:
        boundary = min(bounds, key=functools.cmp_to_key(cmp))
        ordered = [k for k in ordered if cmp(k, boundary) <= 0]
    page = ordered[:size]
    buckets = []
    for k in page:
        entry = groups[_composite_keyjson(k)]
        b = {"key": dict(zip(names, k)), "doc_count": entry["c"]}
        _finalize_sub(sub, entry, b)
        buckets.append(b)
    A._apply_parent_pipelines(
        A._split_parent_pipelines(sub)[1] if sub else {}, buckets)
    out: Dict[str, Any] = {"buckets": buckets}
    if buckets:
        out["after_key"] = buckets[-1]["key"]
    return out


# ---------------------------------------------------------------------------
# incremental consumer (coordinator)
# ---------------------------------------------------------------------------

class AggReduceConsumer:
    """The QueryPhaseResultConsumer analogue: consume shard agg
    partials as they arrive, partial-reducing every ``batch_size``
    arrivals so coordinator memory holds at most one batch of partials
    plus one accumulator. Buffered partial bytes charge the ``request``
    breaker (released at each reduce); a trip raises out of
    ``consume`` for the coordinator to fail the search — the
    accumulator itself is bounded by the carrier contract (moments,
    sketches, trimmed pages).

    Telemetry: ``search.agg_reduce.partials`` / ``.batches`` counters
    and a per-family ``search.agg_reduce.latency{family}`` histogram.
    ``num_reduce_phases`` counts partial reduces + the final one (ES
    response field semantics)."""

    def __init__(self, spec: Dict[str, Any],
                 batch_size: Optional[int] = None,
                 breaker=None, metrics=None):
        self.spec = spec
        self.batch_size = max(2, int(batch_size
                                     or DEFAULT_BATCHED_REDUCE_SIZE))
        self.breaker = breaker
        self.metrics = metrics
        self.buffer: List[Dict[str, Any]] = []
        # {} (not None): the per-family slice reduce below must merge
        # name-by-name — a None accumulator would deep-copy the WHOLE
        # first partial on the first slice and then re-merge its other
        # names, double-counting them
        self.acc: Dict[str, Any] = {}
        self.partials_consumed = 0
        self.num_reduce_phases = 0
        self._charged = 0
        self._finished = False

    def consume(self, partial: Optional[Dict[str, Any]],
                size_hint: Optional[int] = None) -> None:
        """``size_hint`` lets the caller pre-size the partial OUTSIDE
        its coordinator lock (payload_size_bytes re-serializes the
        tree — O(partial bytes))."""
        if partial is None or self._finished:
            return
        size = (size_hint if size_hint is not None
                else payload_size_bytes(partial))
        if self.breaker is not None:
            # may raise CircuitBreakingException — the caller fails the
            # search (the reference's consumer does the same)
            self.breaker.add_estimate_bytes_and_maybe_break(
                size, "agg_partials")
        self._charged += size
        self.buffer.append(partial)
        self.partials_consumed += 1
        if self.metrics is not None:
            self.metrics.inc("search.agg_reduce.partials")
        if len(self.buffer) >= self.batch_size:
            self._reduce()

    def _reduce(self) -> None:
        if not self.buffer:
            return
        for name, node in (self.spec or {}).items():
            agg_type, _body, _sub = A._split_node(name, node)
            if agg_type in A.PIPELINE_AGGS \
                    or agg_type in A.PARENT_PIPELINES:
                continue
            t0 = time.monotonic()
            slice_spec = {name: node}
            for p in self.buffer:
                self.acc = merge_partials(slice_spec, self.acc, p)
            if self.metrics is not None:
                self.metrics.observe(
                    "search.agg_reduce.latency",
                    (time.monotonic() - t0) * 1000.0,
                    family=agg_type)
        self.buffer.clear()
        self.num_reduce_phases += 1
        if self.metrics is not None:
            self.metrics.inc("search.agg_reduce.batches")
        self._release()

    def _release(self) -> None:
        if self.breaker is not None and self._charged:
            self.breaker.release(self._charged)
        self._charged = 0

    def finish(self) -> Tuple[Optional[Dict[str, Any]], int]:
        """Final reduce of the remainder; returns (accumulator,
        num_reduce_phases) with the final phase counted. Idempotent."""
        if not self._finished:
            self._reduce()
            self.num_reduce_phases += 1   # the final (finalize) phase
            self._release()
            self._finished = True
        return self.acc, self.num_reduce_phases

    def close(self) -> None:
        """Release any outstanding breaker charge without reducing —
        the failure-path seam (a search completing with an error must
        not leave buffered partial bytes charged for the process
        lifetime). Idempotent; a normal finish() already released."""
        self.buffer.clear()
        self._release()
        self._finished = True
