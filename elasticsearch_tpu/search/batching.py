"""Continuous batching of plan-path query launches.

SURVEY.md §7 hard part 5: per-launch overhead (pathological under the
axon tunnel's post-readback ~100ms mode, real on any runtime) must
amortize over many queries. The reference's answer is a thread pool
(`search` pool, ThreadPool.java:117-181 — thread-per-shard-request);
the TPU-native answer is **batched launches**: concurrent requests with
the same kernel shape coalesce into one vmapped execution
(ops/plan.py plan_topk_batch) and share a single device round-trip.

Leader/follower protocol (no background threads, no idle latency tax):
the first request to arrive for a shape becomes the leader; while the
leader's launch is in flight, later arrivals queue; whoever arrives
first after the pop leads the next batch and takes the whole queue with
it. Under load the batch size self-tunes to the launch latency —
classic continuous batching; when idle, a single query runs alone with
zero added wait.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.search.plan import BoundPlan, execute_bound

_Q_BUCKETS = (1, 2, 4, 8, 16, 32)


def _q_bucket(n: int) -> int:
    for b in _Q_BUCKETS:
        if n <= b:
            return b
    return _Q_BUCKETS[-1]


class _Entry:
    __slots__ = ("bp", "event", "result", "error")

    def __init__(self, bp: BoundPlan):
        self.bp = bp
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class PlanBatcher:
    """Shape-bucketed batcher for fused plan launches.

    Eligible: no dense mask, no search_after cursor (those run singly —
    the benchmark-class match/bool-of-term-filters plans are all
    eligible). Batches are keyed by (segment identity, stream shapes,
    group-table size, k, combine, k1, b) so stacked launches are
    homogeneous; Q pads to a power-of-two bucket to bound compile count.
    """

    def __init__(self, max_batch: int = 32):
        self.max_batch = max_batch
        self._lock = threading.Lock()
        # launches serialize here; while one is in flight, followers (and
        # the next leader) accumulate — this blocking IS the batching
        # window, self-tuned to the launch latency
        self._launch_lock = threading.Lock()
        self._pending: Dict[tuple, List[_Entry]] = {}
        self.launches = 0          # stats: total device launches
        self.batched_queries = 0   # stats: queries served via batches

    # ------------------------------------------------------------------
    @staticmethod
    def _eligible(bp: BoundPlan, after_score) -> bool:
        return (bp.dense_mask is None and after_score is None
                and not bp.empty)

    @staticmethod
    def _signature(bp: BoundPlan, ctx, k: int, k1: float, b: float) -> tuple:
        return (
            ctx.segment.name, ctx.segment.live_version,
            tuple((id(st.block_docids), int(st.sel_blocks.shape[0]))
                  for st in bp.streams),
            int(bp.group_kind.shape[0]), bp.combine, k,
            round(k1, 6), round(b, 6),
        )

    # ------------------------------------------------------------------
    def execute(self, bp: BoundPlan, ctx, k: int, k1: float, b: float,
                after_score: Optional[float] = None):
        if not self._eligible(bp, after_score):
            return execute_bound(bp, ctx, k, k1, b, after_score)
        sig = self._signature(bp, ctx, k, k1, b)
        entry = _Entry(bp)
        with self._lock:
            q = self._pending.setdefault(sig, [])
            q.append(entry)
            leader = len(q) == 1
        if not leader:
            entry.event.wait()
            if entry.error is not None:
                raise entry.error
            return entry.result
        # leader: wait for the in-flight launch (cohort grows meanwhile),
        # then take the whole queue. Non-leader entries are always popped
        # by a leader that appended before them, so nothing is orphaned.
        with self._launch_lock:
            with self._lock:
                batch = self._pending.pop(sig, [])
            if not batch:
                batch = [entry]
            try:
                for start in range(0, len(batch), self.max_batch):
                    chunk = batch[start:start + self.max_batch]
                    self._run(chunk, ctx, k, k1, b)
            except BaseException as exc:
                for e in batch:
                    if not e.event.is_set():
                        e.error = exc
                        e.event.set()
                raise
        if entry.error is not None:
            raise entry.error
        return entry.result

    # ------------------------------------------------------------------
    def _run(self, batch: List[_Entry], ctx, k: int, k1: float, b: float):
        qn = len(batch)
        bucket = _q_bucket(qn)
        pad = bucket - qn
        bps = [e.bp for e in batch] + [batch[0].bp] * pad

        proto = bps[0]
        streams = []
        for si, st in enumerate(proto.streams):
            streams.append(plan_ops.FieldStream(
                st.block_docids, st.block_tfs, st.doc_lens, st.avg_len,
                jnp.stack([bp.streams[si].sel_blocks for bp in bps]),
                jnp.stack([bp.streams[si].sel_group for bp in bps]),
                jnp.stack([bp.streams[si].sel_sub for bp in bps]),
                jnp.stack([bp.streams[si].sel_weight for bp in bps]),
                jnp.stack([bp.streams[si].sel_const for bp in bps])))
        gk = np.stack([bp.group_kind for bp in bps])
        gr = np.stack([bp.group_req for bp in bps])
        gc = np.stack([bp.group_const for bp in bps])
        nm = np.asarray([bp.n_must for bp in bps], np.int32)
        nf = np.asarray([bp.n_filter for bp in bps], np.int32)
        ms = np.asarray([bp.msm for bp in bps], np.int32)
        bo = np.asarray([bp.bonus for bp in bps], np.float32)
        ti = np.asarray([bp.tie for bp in bps], np.float32)

        packed = plan_ops.plan_topk_batch(
            streams, gk, gr, gc, ctx.live, nm, nf, ms, bo, ti,
            k1=k1, b=b, k=k, combine=proto.combine)
        # ONE readback for the whole batch (rows are packed buffers)
        rows = np.asarray(packed)
        self.launches += 1
        self.batched_queries += qn
        for i, e in enumerate(batch):
            e.result = plan_ops.unpack_result(rows[i], k)
            e.event.set()

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        return {
            "launches": self.launches,
            "batched_queries": self.batched_queries,
            "avg_batch": (self.batched_queries / self.launches
                          if self.launches else 0.0),
        }
