"""Continuous batching of plan-path query launches.

SURVEY.md §7 hard part 5: per-launch overhead (pathological under the
axon tunnel's post-readback ~100ms mode, real on any runtime) must
amortize over many queries. The reference's answer is a thread pool
(`search` pool, ThreadPool.java:117-181 — thread-per-shard-request);
the TPU-native answer is **batched launches**: concurrent requests with
the same kernel shape coalesce into one vmapped execution
(ops/plan.py plan_topk_batch) and share a single device round-trip.

Leader/follower protocol (no background threads): the first request to
arrive for a shape becomes the leader; while the leader's launch is in
flight, later arrivals queue; whoever arrives first after the pop leads
the next batch and takes the whole queue with it. Under load the batch
size self-tunes to the launch latency (plus an explicit wait, a
fraction of the measured round-trip, taken only when other requests are
pending) — classic continuous batching; a truly idle query still runs
alone with zero added wait.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops.plan import unpack_ids as _unpack_ids

from elasticsearch_tpu.ops import device as device_ops
from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.search.plan import BoundPlan, execute_bound
from elasticsearch_tpu.telemetry import flightrecorder as _flight

_Q_BUCKETS = (1, 2, 4, 8, 16, 32)


def _q_bucket(n: int) -> int:
    for b in _Q_BUCKETS:
        if n <= b:
            return b
    return _Q_BUCKETS[-1]


# NB coalescing tiers: plans whose per-stream selection widths land in
# the same power-of-FOUR tier share a batch signature and pad to the
# tier width, so slightly-different-NB queries (the common mix) coalesce
# into one launch instead of fragmenting into per-pow2 cohorts. Power of
# four bounds the padding waste at 4x device lanes — and only for the
# smallest plan of the cohort; a pow2 ladder would double the signature
# count for ~zero extra coalescing.
_NB_TIER_FLOOR = 64


def _nb_tier(n: int) -> int:
    t = _NB_TIER_FLOOR
    while t < n:
        t *= 4
    return t


class _Entry:
    __slots__ = ("bp", "event", "result", "error", "profiled", "t_enq",
                 "meta", "t_fr", "tenant", "wclass")

    def __init__(self, bp: BoundPlan, profiled: bool = False,
                 t_enq: int = 0, t_fr: float = 0.0,
                 tenant: Optional[str] = None,
                 wclass: Optional[str] = None):
        self.bp = bp
        # the enqueuing request's ambient tenant: cohort occupancy is
        # charged per SLOT, so a hog filling the batch window is
        # attributable even though the launch itself is shared
        self.tenant = tenant
        # and its ambient workload class, for the same per-slot
        # attribution by request kind
        self.wclass = wclass
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        # per-request device attribution (`profile: true` only): the
        # caller flags its entry at enqueue; _run stamps cohort meta
        # (kernel, cohort width, padding waste, launch/readback) only
        # for flagged entries — the profile-off hot path allocates
        # nothing extra
        self.profiled = profiled
        self.t_enq = t_enq
        # enqueue stamp on the flight recorder's clock (always-on when
        # a recorder is ambient): the cohort's queue-wait provenance
        self.t_fr = t_fr
        self.meta: Optional[Dict[str, object]] = None


class PlanBatcher:
    """Shape-bucketed batcher for fused plan launches.

    Eligible: everything but search_after cursors and ad-hoc dense
    masks — plans whose dense mask is a CACHED composed filter column
    batch too, cohorted by the mask's identity so one [ND] column
    serves the launch. Batches are keyed by (segment identity, stream
    shapes, group-table size, k, combine, mask identity, k1, b) so
    stacked launches are homogeneous; Q pads to a power-of-two bucket
    to bound compile count. Under a slow transport the leader waits a
    fraction of the measured launch latency — only when other requests
    are already pending — so cohorts grow without taxing idle queries.
    """

    def __init__(self, max_batch: int = 64, max_concurrent: int = 8,
                 adaptive_flush_s: float = 0.002):
        self.max_batch = min(max_batch, _Q_BUCKETS[-1])
        self._lock = threading.Lock()
        # Launches used to serialize behind one lock; under a transport
        # with a high per-sync latency floor (the axon tunnel degrades
        # every device sync to ~117ms once any d2h transfer has
        # happened) that caps throughput at batch/floor. Syncs OVERLAP
        # across threads, so a bounded semaphore lets several batched
        # launches ride the floor concurrently — the wait in acquire()
        # is still the batching window that grows cohorts under load.
        self._launch_slots = threading.BoundedSemaphore(max_concurrent)
        self._pending: Dict[tuple, List[_Entry]] = {}
        self.launches = 0          # stats: total device launches
        self.batched_queries = 0   # stats: queries served via batches
        self.batch_hist: Dict[int, int] = {}   # pow2 batch-size counts
        # EMA of launch+readback latency: when the device round-trip is
        # slow (the tunnel's ~120ms sync floor), leaders WAIT a fraction
        # of it before popping the queue so cohorts grow — the classic
        # continuous-batching window, sized from measurement instead of
        # a fixed knob. Fast devices (real local TPU: sub-ms) never wait.
        self._lat_ema = 0.0
        # adaptive flush: even on a fast device, a leader that sees
        # OTHER work pending holds the pop for up to this long so the
        # cohort fills — trading ≤~2 ms of p50 for materially larger
        # batches under load (0 disables)
        self.adaptive_flush_s = float(adaptive_flush_s)
        # replica-axis fan-out (opt-in; a MeshSearchBackend wired by the
        # service): cohorts split their query axis over a ("replica",)
        # device mesh — corpus replicated, per-query rows sharded — and
        # the SAME kernel runs partitioned by GSPMD, so per-query
        # results stay byte-identical to the single-device launch
        self.mesh = None
        self.mesh_cohorts = 0     # stats: cohorts launched replica-sharded
        # optional TenantAccounting sink: one cohort slot per entry
        self.tenants = None
        # optional WorkloadAccounting sink: same per-slot charge keyed
        # by request class
        self.workloads = None

    # ------------------------------------------------------------------
    @staticmethod
    def _eligible(bp: BoundPlan, after_score) -> bool:
        # dense plans batch when their mask is the CACHED shared object
        # (one [ND] column serves the cohort); ad-hoc device-column
        # masks run singly
        return (after_score is None and not bp.empty
                and (bp.dense_mask is None or bp.dense_shared))

    @staticmethod
    def _signature(bp: BoundPlan, ctx, k: int, k1: float, b: float) -> tuple:
        # selection widths key by COALESCING TIER, not exact width:
        # plans whose NB landed in different power-of-two buckets (the
        # impact-selected mix) still share a cohort; _run pads every
        # member to the widest member's bucket (zero-block selections
        # with weight 0 are inert in the kernel)
        return (
            ctx.segment.name, ctx.segment.live_version,
            tuple((id(st.block_docids), _nb_tier(int(st.sel_blocks.shape[0])))
                  for st in bp.streams),
            int(bp.group_kind.shape[0]), bp.combine, k,
            id(bp.dense_mask) if bp.dense_mask is not None else None,
            id(bp.script_fn) if bp.script_fn is not None else None,
            round(k1, 6), round(b, 6),
        )

    # ------------------------------------------------------------------
    def execute(self, bp: BoundPlan, ctx, k: int, k1: float, b: float,
                after_score: Optional[float] = None):
        from elasticsearch_tpu.search import profile as _prof
        from elasticsearch_tpu.telemetry import context as _telectx
        profiled = _prof.recording()
        if not self._eligible(bp, after_score):
            return execute_bound(bp, ctx, k, k1, b, after_score)
        sig = self._signature(bp, ctx, k, k1, b)
        fr = _flight.current()
        entry = _Entry(bp, profiled=profiled,
                       t_enq=_prof.now_ns() if profiled else 0,
                       t_fr=fr.clock() if fr is not None else 0.0,
                       tenant=_telectx.current_tenant(),
                       wclass=_telectx.current_workload_class())
        with self._lock:
            q = self._pending.setdefault(sig, [])
            q.append(entry)
            leader = len(q) == 1
        if not leader:
            entry.event.wait()
            if entry.error is not None:
                raise entry.error
            if profiled:
                self._record_attribution(entry)
            return entry.result
        # leader: let the cohort grow while the device is slow, then wait
        # for a launch slot and take the whole queue. Non-leader entries
        # are always popped by a leader that appended before them, so
        # nothing is orphaned. The wait engages only when concurrency is
        # actually present (other work pending) and is STAGED: stop as
        # soon as this signature's cohort fills a max batch — when a
        # launch costs seconds, padding a 3-query cohort to the batch
        # shape wastes ~10x device time, so waiting a fraction of the
        # measured round-trip to fill the cohort is strictly cheaper.
        # On a FAST device the adaptive flush window still holds the pop
        # for ≤~2 ms when other work is pending, so loaded traffic
        # coalesces instead of racing out in cohorts of one.
        window = (min(0.75 * self._lat_ema, 1.5)
                  if self._lat_ema > 0.03 else self.adaptive_flush_s)
        if window > 0.0:
            deadline = time.monotonic() + window
            step = min(0.02, max(window / 4.0, 0.0005))
            while time.monotonic() < deadline:
                with self._lock:
                    mine = len(self._pending.get(sig, ()))
                    busy = (mine > 1 or len(self._pending) > 1
                            or any(len(q) > 1
                                   for q in self._pending.values()))
                if mine >= self.max_batch or not busy:
                    break
                time.sleep(step)
        with self._launch_slots:
            with self._lock:
                batch = self._pending.pop(sig, [])
            if not batch:
                batch = [entry]
            try:
                for start in range(0, len(batch), self.max_batch):
                    chunk = batch[start:start + self.max_batch]
                    self._run(chunk, ctx, k, k1, b)
            except BaseException as exc:
                for e in batch:
                    if not e.event.is_set():
                        e.error = exc
                        e.event.set()
                raise
        if entry.error is not None:
            raise entry.error
        if profiled:
            self._record_attribution(entry)
        return entry.result

    # ------------------------------------------------------------------
    @staticmethod
    def _record_attribution(entry: _Entry) -> None:
        """Fold the cohort meta `_run` stamped on this entry into the
        caller's active profile recorder, adding the batcher wait (time
        between enqueue and the completed launch, minus the launch
        itself — the continuous-batching cost this request paid to ride
        a cohort)."""
        from elasticsearch_tpu.search import profile as _prof
        meta = entry.meta
        if meta is None:
            return
        total_ms = max(0.0, (_prof.now_ns() - entry.t_enq) / 1e6)
        rec = dict(meta)
        rec["batch_wait_ms"] = round(
            max(0.0, total_ms - float(rec.get("launch_ms", 0.0))), 3)
        _prof.record_device(rec)

    # ------------------------------------------------------------------
    @staticmethod
    def _pad1(a: np.ndarray, width: int, fill) -> np.ndarray:
        if a.shape[0] == width:
            return a
        out = np.full(width, fill, a.dtype)
        out[:a.shape[0]] = a
        return out

    def _run(self, batch: List[_Entry], ctx, k: int, k1: float, b: float):
        qn = len(batch)
        bucket = _q_bucket(qn)
        pad = bucket - qn
        bps = [e.bp for e in batch] + [batch[0].bp] * pad

        proto = bps[0]
        streams = []
        ngpad = int(proto.group_kind.shape[0])
        for si, st in enumerate(proto.streams):
            # a tier-coalesced cohort pads every member to the WIDEST
            # member's (power-of-two) selection width: pads select the
            # reserved zero block with weight 0 — all-zero tfs, so the
            # kernel never counts them for presence or score (the
            # bind_plan pad convention)
            width = max(int(bp.streams[si].sel_blocks.shape[0])
                        for bp in bps)
            zero_block = int(st.block_docids.shape[0]) - 1
            # host-side np.stack (µs): selections are numpy; the jit
            # boundary uploads the stacked batch asynchronously
            streams.append(plan_ops.FieldStream(
                st.block_docids, st.block_tfs, st.doc_lens, st.avg_len,
                np.stack([self._pad1(bp.streams[si].sel_blocks, width,
                                     zero_block) for bp in bps]),
                np.stack([self._pad1(bp.streams[si].sel_group, width,
                                     ngpad) for bp in bps]),
                np.stack([self._pad1(bp.streams[si].sel_sub, width, 0)
                          for bp in bps]),
                np.stack([self._pad1(bp.streams[si].sel_weight, width,
                                     0.0) for bp in bps]),
                np.stack([self._pad1(bp.streams[si].sel_const, width,
                                     False) for bp in bps])))
        gk = np.stack([bp.group_kind for bp in bps])
        gr = np.stack([bp.group_req for bp in bps])
        gc = np.stack([bp.group_const for bp in bps])
        nm = np.asarray([bp.n_must for bp in bps], np.int32)
        nf = np.asarray([bp.n_filter for bp in bps], np.int32)
        ms = np.asarray([bp.msm for bp in bps], np.int32)
        bo = np.asarray([bp.bonus for bp in bps], np.float32)
        ti = np.asarray([bp.tie for bp in bps], np.float32)
        live = ctx.live
        rmesh = None
        if (self.mesh is not None and proto.dense_mask is None
                and proto.script_fn is None):
            # replica fan-out: corpus arrays ride as replicated (P())
            # handles, every per-query row shards P("replica") — the
            # identical jitted kernel then partitions over the Q axis
            rmesh = self.mesh.replica_mesh_for(bucket)
        if rmesh is not None:
            mb = self.mesh
            streams = [plan_ops.FieldStream(
                mb.replicated(rmesh, st.block_docids),
                mb.replicated(rmesh, st.block_tfs),
                mb.replicated(rmesh, st.doc_lens),
                mb.replicated(rmesh, st.avg_len),
                mb.shard_rows(rmesh, st.sel_blocks),
                mb.shard_rows(rmesh, st.sel_group),
                mb.shard_rows(rmesh, st.sel_sub),
                mb.shard_rows(rmesh, st.sel_weight),
                mb.shard_rows(rmesh, st.sel_const))
                for st in streams]
            live = mb.replicated(rmesh, ctx.live)
            gk, gr, gc = (mb.shard_rows(rmesh, a) for a in (gk, gr, gc))
            nm, nf, ms, bo, ti = (mb.shard_rows(rmesh, a)
                                  for a in (nm, nf, ms, bo, ti))
        any_prof = any(e.profiled for e in batch)
        t0p = 0
        if any_prof:
            from elasticsearch_tpu.search import profile as _prof
            t0p = _prof.now_ns()
        t0 = time.monotonic()
        # flight provenance: annotate the launch inside plan_topk_batch
        # with the cohort's fill/capacity + the queue wait its OLDEST
        # rider paid (recorder clock — virtual under the deterministic
        # harness), and route the single packed readback through the
        # tracked ops/device funnel
        fr = _flight.current()
        enq = [e.t_fr for e in batch if e.t_fr]
        qw_ns = (int(max(0.0, fr.clock() - min(enq)) * 1e9)
                 if fr is not None and enq else 0)
        with _flight.annotate_launch(qn, bucket, queue_wait_ns=qw_ns):
            packed = plan_ops.plan_topk_batch(
                streams, gk, gr, gc, live, nm, nf, ms, bo, ti,
                k1=k1, b=b, k=k, combine=proto.combine,
                # cohort-shared filter column + script (signature keys
                # on their identities)
                dense_mask=proto.dense_mask, script_fn=proto.script_fn)
        # ONE readback for the whole batch (rows are packed buffers)
        rows = device_ops.readback("search.batching.plan_cohort", packed,
                                   profile=False)
        dt = time.monotonic() - t0
        if dt < 5.0:   # ignore compile-length outliers (first launches)
            self._lat_ema = (dt if self._lat_ema == 0.0
                             else 0.8 * self._lat_ema + 0.2 * dt)
        self.launches += 1
        self.batched_queries += qn
        self.batch_hist[bucket] = self.batch_hist.get(bucket, 0) + 1
        if self.tenants is not None:
            # integer slot counts only — replay-deterministic
            for e in batch:
                self.tenants.record_cohort(e.tenant)
        if self.workloads is not None:
            for e in batch:
                self.workloads.record_cohort(e.wclass)
        if rmesh is not None:
            self.mesh_cohorts += 1
            self.mesh._dispatch("replica", qn)
        if any_prof:
            # cohort meta for `profile: true` device attribution — the
            # launch is timed on the profile clock (virtual under the
            # deterministic harness → replay-identical trees); padding
            # waste is per entry: the padded selection slots the cohort
            # tier forced on THIS plan, plus the Q-bucket pad rows
            launch_ms = round((_prof.now_ns() - t0p) / 1e6, 3)
            widths = [int(st.sel_blocks.shape[1]) for st in streams]
            row_slots = sum(widths)        # one cohort row's padded slots
            readback = int(rows[0].nbytes)
            for e in batch:
                if not e.profiled:
                    continue
                # per-entry waste: the tier-padded slots of THIS plan's
                # row that its own selection did not fill (the Q-bucket
                # pad rows are cohort overhead, visible via q_bucket
                # vs cohort)
                own = sum(int(st.sel_blocks.shape[0])
                          for st in e.bp.streams)
                e.meta = {
                    "kernel": "plan_topk_batch",
                    "cohort": qn,
                    **({"mesh_shape":
                        {"replica": rmesh.devices.size}}
                       if rmesh is not None else {}),
                    "q_bucket": bucket,
                    "nb_bucket": max(widths) if widths else 0,
                    "nb_selected": own,
                    "padding_waste_pct": round(
                        100.0 * (1.0 - own / row_slots), 1)
                    if row_slots else 0.0,
                    "launch_ms": launch_ms,
                    "readback_bytes": readback,
                }
        for i, e in enumerate(batch):
            e.result = plan_ops.unpack_result(rows[i], k)
            e.event.set()

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        return {
            "launches": self.launches,
            "batched_queries": self.batched_queries,
            "avg_batch": (self.batched_queries / self.launches
                          if self.launches else 0.0),
            "batch_hist": {str(kk): v for kk, v
                           in sorted(self.batch_hist.items())},
            "mesh_cohorts": self.mesh_cohorts,
        }


# ---------------------------------------------------------------------------
# kNN branch batching
# ---------------------------------------------------------------------------

_CUT_BUCKETS = (128, 256, 512, 1024, 2048, 4096)


def _cut_bucket(n: int) -> int:
    for b in _CUT_BUCKETS:
        if n <= b:
            return b
    return _CUT_BUCKETS[-1]


class _KnnEntry:
    __slots__ = ("qvec", "cut", "event", "result", "error", "profiled",
                 "t_enq", "meta", "t_fr", "tenant", "wclass")

    def __init__(self, qvec: np.ndarray, cut: int,
                 profiled: bool = False, t_enq: int = 0,
                 t_fr: float = 0.0, tenant: Optional[str] = None,
                 wclass: Optional[str] = None):
        self.qvec = qvec
        self.cut = cut
        self.tenant = tenant
        self.wclass = wclass
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.profiled = profiled
        self.t_enq = t_enq
        self.t_fr = t_fr
        self.meta: Optional[Dict[str, object]] = None


class KnnBatcher:
    """Continuous batching for kNN branch launches — the vector
    analogue of :class:`PlanBatcher`. Concurrent kNN queries against
    the same device slab coalesce into ONE
    ``ops.vector.knn_nominate_batch`` launch ([Q, D] matmul + batched
    top-k) and share a single packed readback; without this every
    hybrid-RRF request pays its own degraded-mode matvec chain
    (BASELINE config 5's serving cost). Scores and int32 docids pack
    into one float32 buffer (bitcast) so the cohort syncs exactly once.
    """

    def __init__(self, max_batch: int = 64, max_concurrent: int = 8,
                 adaptive_flush_s: float = 0.002):
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._launch_slots = threading.BoundedSemaphore(max_concurrent)
        self._pending: Dict[tuple, List[_KnnEntry]] = {}
        self.launches = 0
        self.batched_queries = 0
        self._lat_ema = 0.0
        self.adaptive_flush_s = float(adaptive_flush_s)
        self.tenants = None    # optional TenantAccounting sink
        self.workloads = None  # optional WorkloadAccounting sink

    def topk(self, dv, live, qvec: np.ndarray, cut: int,
             host_vectors=None) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``cut`` (scores, docids) for one query vector against a
        DeviceVectors slab, honoring the segment's device ``live`` mask
        (deletes). ``host_vectors`` (the segment's f32 host copy)
        enables the exact re-rank when the slab is quantized
        (KnnQuery._exact_rerank parity). The cut caps at the slab's
        padded row count — lax.top_k cannot exceed the axis."""
        from elasticsearch_tpu.search import profile as _prof
        from elasticsearch_tpu.telemetry import context as _telectx
        profiled = _prof.recording()
        nd = int(dv.vectors.shape[0])
        bucket_cut = min(_cut_bucket(cut), nd)
        sig = (id(dv.vectors), id(live), dv.similarity, bucket_cut,
               int(qvec.shape[0]))
        fr = _flight.current()
        entry = _KnnEntry(np.asarray(qvec, np.float32), cut,
                          profiled=profiled,
                          t_enq=_prof.now_ns() if profiled else 0,
                          t_fr=fr.clock() if fr is not None else 0.0,
                          tenant=_telectx.current_tenant(),
                          wclass=_telectx.current_workload_class())
        with self._lock:
            q = self._pending.setdefault(sig, [])
            q.append(entry)
            leader = len(q) == 1
        if not leader:
            entry.event.wait()
            if entry.error is not None:
                raise entry.error
            if profiled:
                PlanBatcher._record_attribution(entry)
            return self._finish(entry, dv, host_vectors)
        window = (min(0.75 * self._lat_ema, 1.5)
                  if self._lat_ema > 0.03 else self.adaptive_flush_s)
        if window > 0.0:
            deadline = time.monotonic() + window
            step = min(0.02, max(window / 4.0, 0.0005))
            while time.monotonic() < deadline:
                with self._lock:
                    mine = len(self._pending.get(sig, ()))
                    busy = (mine > 1 or len(self._pending) > 1
                            or any(len(qq) > 1
                                   for qq in self._pending.values()))
                if mine >= self.max_batch or not busy:
                    break
                time.sleep(step)
        with self._launch_slots:
            with self._lock:
                batch = self._pending.pop(sig, [])
            if not batch:
                batch = [entry]
            try:
                for start in range(0, len(batch), self.max_batch):
                    self._run(batch[start:start + self.max_batch], dv,
                              live, bucket_cut)
            except BaseException as exc:
                for e in batch:
                    if not e.event.is_set():
                        e.error = exc
                        e.event.set()
                raise
        if entry.error is not None:
            raise entry.error
        if profiled:
            PlanBatcher._record_attribution(entry)
        return self._finish(entry, dv, host_vectors)

    # ------------------------------------------------------------------
    def _run(self, batch: List[_KnnEntry], dv, live, cut: int):
        from elasticsearch_tpu.ops import vector as vec_ops
        import jax
        # the cohort's [Qb, ND] float32 score matrix must fit next to
        # the slab (an 8M-doc slab already holds ~11.5 GiB of HBM) —
        # cap Qb so the ephemeral stays ≤ ~1 GiB
        nd = int(dv.vectors.shape[0])
        cap = max(1, (1 << 28) // max(nd, 1))
        allowed = max((b for b in _Q_BUCKETS if b <= cap), default=1)
        for start in range(0, len(batch), allowed):
            chunk = batch[start:start + allowed]
            qn = len(chunk)
            bucket = min(_q_bucket(qn), allowed)
            qs = np.stack([e.qvec for e in chunk]
                          + [chunk[0].qvec] * (bucket - qn))
            any_prof = any(e.profiled for e in chunk)
            t0p = 0
            if any_prof:
                from elasticsearch_tpu.search import profile as _prof
                t0p = _prof.now_ns()
            t0 = time.monotonic()
            fr = _flight.current()
            enq = [e.t_fr for e in chunk if e.t_fr]
            qw_ns = (int(max(0.0, fr.clock() - min(enq)) * 1e9)
                     if fr is not None and enq else 0)
            with _flight.annotate_launch(qn, bucket,
                                         queue_wait_ns=qw_ns):
                top_s, top_i = vec_ops.knn_nominate_batch(
                    jnp.asarray(qs), dv.vectors, dv.sq_norms,
                    dv.has_value, live, dv.similarity, cut)
            # ONE packed readback: ids as float CASTS (exact < 2^24;
            # the axon runtime miscompiles multi-bitcast concats —
            # ops/plan.pack_result)
            packed = jnp.concatenate(
                [top_s, top_i.astype(jnp.float32)], axis=1)
            rows = device_ops.readback("search.batching.knn_cohort",
                                       packed, profile=False)
            dt = time.monotonic() - t0
            with self._lock:
                if dt < 5.0:
                    self._lat_ema = (dt if self._lat_ema == 0.0
                                     else 0.8 * self._lat_ema + 0.2 * dt)
                self.launches += 1
                self.batched_queries += qn
            if self.tenants is not None:
                for e in chunk:
                    self.tenants.record_cohort(e.tenant)
            if self.workloads is not None:
                for e in chunk:
                    self.workloads.record_cohort(e.wclass)
            if any_prof:
                launch_ms = round((_prof.now_ns() - t0p) / 1e6, 3)
                for e in chunk:
                    if e.profiled:
                        # same semantics as PlanBatcher: per-row slot
                        # waste — the bucketed cut columns this entry's
                        # own request did not need; Q-pad rows stay
                        # visible via q_bucket vs cohort
                        e.meta = {
                            "kernel": "knn_nominate_batch",
                            "cohort": qn,
                            "q_bucket": bucket,
                            "nb_bucket": cut,
                            "padding_waste_pct": round(
                                100.0 * (1.0 - min(e.cut, cut) / cut),
                                1) if cut else 0.0,
                            "launch_ms": launch_ms,
                            "readback_bytes": int(rows[0].nbytes),
                        }
            for i, e in enumerate(chunk):
                scores = rows[i, :cut].copy()
                ids = _unpack_ids(rows[i, cut:])
                e.result = (scores, ids)
                e.event.set()

    # ------------------------------------------------------------------
    def _finish(self, entry: _KnnEntry, dv,
                host_vectors) -> Tuple[np.ndarray, np.ndarray]:
        scores, ids = entry.result
        ok = np.isfinite(scores)
        scores, ids = scores[ok], ids[ok]
        if dv.vectors.dtype != jnp.float32 and host_vectors is not None:
            # exact f32 re-rank of the nominated candidates
            # (KnnQuery._exact_rerank parity: bf16 only NOMINATES)
            valid = ids < host_vectors.shape[0]
            scores, ids = scores[valid], ids[valid]
            cand = host_vectors[ids].astype(np.float32)
            q32 = entry.qvec.astype(np.float32)
            if dv.similarity == "cosine":
                nrm = (np.linalg.norm(cand, axis=1)
                       * np.linalg.norm(q32))
                raw = cand @ q32 / np.where(nrm > 0, nrm, 1.0)
                scores = (1.0 + raw) / 2.0
            elif dv.similarity == "dot_product":
                scores = (1.0 + cand @ q32) / 2.0
            else:
                d2 = np.sum((cand - q32[None, :]) ** 2, axis=1)
                scores = 1.0 / (1.0 + d2)
        order = np.lexsort((ids, -scores))[: entry.cut]
        return scores[order], ids[order]

    def stats(self) -> Dict[str, float]:
        return {
            "knn_launches": self.launches,
            "knn_batched_queries": self.batched_queries,
            "knn_avg_batch": (self.batched_queries / self.launches
                              if self.launches else 0.0),
        }
