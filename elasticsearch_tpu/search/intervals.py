"""Interval matching over token streams (intervals + span queries).

Mirrors the reference's intervals query (ref: index/query/
IntervalQueryBuilder + Lucene's minimal-interval semantics
IntervalsSource) and the classic span family (SpanNearQueryBuilder
et al., which the reference registers alongside, SURVEY.md §2.1 "Query
DSL"). TPU-first split, same as phrases (search/phrase.py): the device
runs the coarse docid filter over postings blocks; the exact
minimal-interval algebra below runs host-side over only the surviving
candidates' positional token rows.

An interval is (start, end) inclusive token positions. Sources compute
MINIMAL intervals (no interval contains another) per candidate row:

  - term:    every position of a term
  - match:   n terms, ordered or unordered, with max_gaps
  - any_of:  union of child intervals (minimalized)
  - all_of:  one interval from each child, ordered/unordered, max_gaps
  - not_containing / first-ending-before etc. via filters

Span queries translate onto these: span_term → term, span_or → any_of,
span_near → all_of(ordered=in_order, max_gaps=slop), span_first →
filter end < n, span_not → drop intervals overlapping the exclude set.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Interval = Tuple[int, int]


def _minimalize(intervals: List[Interval]) -> List[Interval]:
    """Drop intervals that strictly contain another interval (Lucene keeps
    only minimal ones); result sorted by (start, end)."""
    ivs = sorted(set(intervals))
    return [a for a in ivs
            if not any(b != a and b[0] >= a[0] and b[1] <= a[1]
                       for b in ivs)]


def term_intervals(row: Sequence[int], tid: int) -> List[Interval]:
    return [(int(p), int(p)) for p in np.nonzero(
        np.asarray(row) == tid)[0]]


def match_intervals(row: Sequence[int], tids: Sequence[int],
                    ordered: bool, max_gaps: int) -> List[Interval]:
    """Minimal intervals covering all terms (ordered or any order)."""
    if not tids:
        return []
    if len(tids) == 1:
        return term_intervals(row, tids[0])
    pos_lists = [np.nonzero(np.asarray(row) == t)[0].tolist()
                 for t in tids]
    if any(not pl for pl in pos_lists):
        return []
    out: List[Interval] = []
    if ordered:
        # for each start of the first term, greedily chain the rest
        for p0 in pos_lists[0]:
            cur = p0
            ok = True
            for pl in pos_lists[1:]:
                nxt = next((p for p in pl if p > cur), None)
                if nxt is None:
                    ok = False
                    break
                cur = nxt
            if ok:
                out.append((p0, cur))
    else:
        # classic minimal-window sweep over the heads of each list
        idx = [0] * len(pos_lists)
        while True:
            heads = [pos_lists[j][idx[j]] for j in range(len(pos_lists))]
            if len(set(heads)) == len(heads):       # distinct positions
                out.append((min(heads), max(heads)))
            j_min = min(range(len(heads)), key=lambda j: heads[j])
            idx[j_min] += 1
            if idx[j_min] >= len(pos_lists[j_min]):
                break
    out = _minimalize(out)
    if max_gaps >= 0:
        n = len(tids)
        out = [(s, e) for s, e in out if (e - s + 1 - n) <= max_gaps]
    return out


def all_of_intervals(children: List[List[Interval]], ordered: bool,
                     max_gaps: int) -> List[Interval]:
    """One interval from each child; ordered children must not overlap
    and appear in sequence. Gaps measured between consecutive child
    intervals (ordered) or as window slack (unordered)."""
    if any(not c for c in children):
        return []
    out: List[Interval] = []
    if ordered:
        for s0, e0 in children[0]:
            # greedily chain the remaining children after this first
            # interval (first fit — Lucene's minimal-interval greediness)
            def rest(ci: int, prev_end: int) -> bool:
                if ci == len(children):
                    out.append((s0, prev_end))
                    return True
                for s, e in children[ci]:
                    if s > prev_end:
                        if (max_gaps >= 0
                                and (s - prev_end - 1) > max_gaps):
                            return False
                        return rest(ci + 1, e)
                return False

            rest(1, e0)
    else:
        # linear heads-sweep over the children's (sorted) interval lists —
        # the match_intervals unordered pattern lifted to intervals; the
        # itertools.product alternative is exponential per candidate doc
        lists = [sorted(c) for c in children]
        idx = [0] * len(lists)
        while True:
            heads = [lists[j][idx[j]] for j in range(len(lists))]
            s = min(h[0] for h in heads)
            e = max(h[1] for h in heads)
            width = e - s + 1
            covered = sum(min(he, e) - max(hs, s) + 1
                          for hs, he in heads)
            if max_gaps < 0 or (width - min(covered, width)) <= max_gaps:
                out.append((s, e))
            j_min = min(range(len(heads)), key=lambda j: heads[j][0])
            idx[j_min] += 1
            if idx[j_min] >= len(lists[j_min]):
                break
    return _minimalize(out)


def any_of_intervals(children: List[List[Interval]]) -> List[Interval]:
    out: List[Interval] = []
    for c in children:
        out.extend(c)
    return _minimalize(out)


def not_overlapping(include: List[Interval],
                    exclude: List[Interval]) -> List[Interval]:
    def overlaps(a: Interval, b: Interval) -> bool:
        return a[0] <= b[1] and b[0] <= a[1]
    return [iv for iv in include
            if not any(overlaps(iv, ex) for ex in exclude)]


def containing(big: List[Interval],
               small: List[Interval]) -> List[Interval]:
    """Intervals from `big` that contain at least one of `small`
    (span_containing)."""
    return [b for b in big
            if any(s[0] >= b[0] and s[1] <= b[1] for s in small)]


def within(small: List[Interval], big: List[Interval]) -> List[Interval]:
    """Intervals from `small` that lie within one of `big` (span_within)."""
    return [s for s in small
            if any(s[0] >= b[0] and s[1] <= b[1] for b in big)]


# ---------------------------------------------------------------------------
# rule tree evaluation
# ---------------------------------------------------------------------------

def evaluate_rule(rule: Dict[str, Any], row: Sequence[int],
                  term_id: Callable[[str], int],
                  expand_prefix: Callable[[str], List[int]],
                  rows: Optional[Dict[str, Sequence[int]]] = None
                  ) -> List[Interval]:
    """Evaluate an intervals rule tree for one candidate row. Nodes
    marked ``_src_field`` (field_masking_span subtrees) switch the doc's
    token row to that field's via ``rows`` — positions from the source
    field combine with the enclosing field's spans, the Lucene
    FieldMaskingSpanQuery contract (same-position subfields)."""
    (kind, spec), = ((k, v) for k, v in rule.items()
                     if k not in ("boost",))
    if (isinstance(spec, dict) and rows is not None
            and spec.get("_src_field") is not None):
        row = rows.get(str(spec["_src_field"]), row)
    if kind == "term":                        # internal: single term id
        return term_intervals(row, spec)
    if kind == "match":
        tids = spec["_tids"]
        out = match_intervals(row, tids,
                              bool(spec.get("ordered", False)),
                              int(spec.get("max_gaps", -1)))
        flt = spec.get("filter")
        if flt:
            out = _apply_filter(out, flt, row, term_id, expand_prefix,
                                rows)
        return out
    if kind == "prefix":
        tids = spec["_tids"]
        return any_of_intervals([term_intervals(row, t) for t in tids])
    if kind == "any_of":
        out = any_of_intervals([
            evaluate_rule(r, row, term_id, expand_prefix, rows)
            for r in spec.get("intervals", [])])
        flt = spec.get("filter")
        if flt:
            out = _apply_filter(out, flt, row, term_id, expand_prefix,
                                rows)
        return out
    if kind == "all_of":
        children = [evaluate_rule(r, row, term_id, expand_prefix, rows)
                    for r in spec.get("intervals", [])]
        out = all_of_intervals(children,
                               bool(spec.get("ordered", False)),
                               int(spec.get("max_gaps", -1)))
        first_end = spec.get("_first_end")
        if first_end is not None:           # span_first: end < n
            out = [iv for iv in out if iv[1] < int(first_end)]
        flt = spec.get("filter")
        if flt:
            out = _apply_filter(out, flt, row, term_id, expand_prefix,
                                rows)
        return out
    raise ValueError(f"unknown intervals rule [{kind}]")


def _apply_filter(intervals: List[Interval], flt: Dict[str, Any],
                  row, term_id, expand_prefix,
                  rows: Optional[Dict[str, Sequence[int]]] = None
                  ) -> List[Interval]:
    """ES intervals filters: not_containing / containing / not_contained_by
    / contained_by / not_overlapping. ``rows`` threads through so
    field-masked subtrees in filter position read their own field."""
    for fkind, frule in flt.items():
        other = evaluate_rule(frule, row, term_id, expand_prefix, rows)
        if fkind == "not_containing":
            intervals = [iv for iv in intervals
                         if not any(o[0] >= iv[0] and o[1] <= iv[1]
                                    for o in other)]
        elif fkind == "containing":
            intervals = containing(intervals, other)
        elif fkind == "contained_by":
            intervals = within(intervals, other)
        elif fkind == "not_contained_by":
            inside = within(intervals, other)
            intervals = [iv for iv in intervals if iv not in inside]
        elif fkind == "not_overlapping":
            intervals = not_overlapping(intervals, other)
        else:
            raise ValueError(f"unknown intervals filter [{fkind}]")
    return intervals
