"""Macro-workload bench harness (Rally-style mixed load over the sim).

``bench.macro.run_macro`` drives a weighted mix of request classes
against a seeded 3-node sim cluster on the deterministic scheduler and
returns a replay-stable result dict — the BENCH json ``macro`` rider
and ``tests/test_macro_workload.py`` both consume it.
"""

from elasticsearch_tpu.bench.macro import run_macro  # noqa: F401
