"""Rally-style macro-workload harness over the deterministic sim.

``run_macro`` drives a weighted mix of request classes — ``interactive``
search (bm25 match / bool / term), ``bulk`` indexing, ``aggs``,
``scroll`` drains, and ``async`` search — against a seeded 3-node sim
cluster with OPEN-LOOP arrival schedules: every request's arrival time
is drawn up front from ``random.Random(seed)`` and fired at that
virtual instant whether or not earlier requests have completed (the
Rally ``target-throughput`` model, not a closed request loop). Each
request carries a tenant tag and its workload class rides the ambient
context rail (telemetry/context.py), so the per-node
``WorkloadAccounting`` tables, the ``/_workload/stats`` fan-out merge,
and the ``workload_slo`` health indicator all observe the SAME run the
returned summary reports.

Mid-run the harness injects the PR-12/14 chaos pair: an explicit
``_cluster/reroute`` primary relocation, then a node stop + restart
(fresh ``ClusterNode`` over the same data dir — gateway reload,
translog replay, re-join). The run must SURVIVE both: every acked bulk
write is re-counted after a final refresh and the loss count must be 0.

Replay-stable by construction: all clocks are the scheduler's virtual
clock, all randomness is the seeded builder, and the transcript rows
append in completion order under the deterministic queue — two
same-seed runs render byte-identical ``json.dumps`` output. The BENCH
json ``macro`` rider banks this dict CPU-side before any device touch.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.telemetry import context as _telectx

TENANTS = ("alpha", "beta", "gamma")

# tighter-than-default per-class objectives (virtual ms): steady-state
# sim RTTs sit just under these, so budget burn localizes to the
# chaos window — which is exactly what workload_slo should surface
MACRO_SLO_OBJECTIVES_MS = {
    "interactive": 150.0,
    "aggs": 250.0,
    "scroll": 500.0,
    "async": 2000.0,
}

_INTERACTIVE_BODIES = (
    {"query": {"match": {"body": "fox"}}, "size": 5},
    {"query": {"bool": {
        "must": [{"match": {"body": "doc"}}],
        "filter": [{"term": {"category": "a"}}]}}, "size": 5},
    {"query": {"term": {"category": "b"}}, "size": 5},
)

_AGGS_BODY = {"size": 0, "aggs": {
    "cats": {"terms": {"field": "category"},
             "aggs": {"avg_p": {"avg": {"field": "price"}}}}}}

_DOCS_MAPPINGS = {"properties": {
    "category": {"type": "keyword"},
    "price": {"type": "double"},
}}


class _MacroCluster:
    """3-node sim cluster with the stop/restart idiom (the
    SimDataCluster shape from the integration suite, inlined here so
    the bench package stays importable without tests/)."""

    def __init__(self, n_nodes: int, root: str, seed: int):
        from elasticsearch_tpu.cluster.node import ClusterNode
        from elasticsearch_tpu.testing.deterministic import (
            DeterministicTaskQueue, DisruptableTransport, SimNetwork)
        from elasticsearch_tpu.transport.transport import DiscoveryNode
        self._ClusterNode = ClusterNode
        self._DisruptableTransport = DisruptableTransport
        self.queue = DeterministicTaskQueue(seed=seed)
        self.network = SimNetwork(self.queue)
        self.nodes = [DiscoveryNode(node_id=f"mw-{i}", name=f"mw{i}")
                      for i in range(n_nodes)]
        self.data_paths = {n.node_id: os.path.join(root, n.name)
                           for n in self.nodes}
        self.cluster_nodes: Dict[str, Any] = {}
        for node in self.nodes:
            self._boot(node)
        for cn in self.cluster_nodes.values():
            cn.start()

    def _boot(self, node):
        cn = self._ClusterNode(
            self._DisruptableTransport(node, self.network), self.queue,
            data_path=self.data_paths[node.node_id],
            seed_nodes=self.nodes,
            initial_master_nodes=[n.name for n in self.nodes],
            rng=self.queue.random)
        cn.telemetry.workload.slo_objectives.update(
            MACRO_SLO_OBJECTIVES_MS)
        self.cluster_nodes[node.node_id] = cn
        return cn

    def stop_node(self, node_id: str):
        """Process exit: stop services, then cut every link so
        in-flight sends fail fast."""
        from elasticsearch_tpu.testing.deterministic import DISCONNECTED
        cn = self.cluster_nodes.pop(node_id)
        cn.stop()
        self.network.isolate(cn.local_node, self.nodes,
                             mode=DISCONNECTED)
        return cn

    def restart_node(self, node_id: str):
        """Fresh ClusterNode over the stopped node's data dir."""
        from elasticsearch_tpu.testing.deterministic import CONNECTED
        node = next(n for n in self.nodes if n.node_id == node_id)
        for other in self.nodes:
            if other.node_id != node_id:
                self.network.set_link(node, other, CONNECTED)
        cn = self._boot(node)
        cn.start()
        return cn

    def run_for(self, seconds: float) -> None:
        self.queue.run_for(seconds)

    def master(self):
        masters = [c for c in self.cluster_nodes.values()
                   if c.is_master()]
        assert len(masters) == 1, \
            f"masters: {[m.local_node.name for m in masters]}"
        return masters[0]

    def stabilise(self, seconds: float = 60):
        self.run_for(seconds)
        return self.master()

    def live_ids(self) -> List[str]:
        return sorted(self.cluster_nodes)

    def call(self, fn: Callable, *args, timeout: float = 60, **kwargs):
        """Closed-loop helper for setup/verification phases only —
        the measured mix itself is issued open-loop."""
        box: Dict[str, Any] = {}

        def on_done(result, err=None):
            box["result"] = result
            box["err"] = err

        fn(*args, **kwargs, on_done=on_done)
        waited = 0.0
        while "result" not in box and "err" not in box \
                and waited < timeout:
            self.run_for(1.0)
            waited += 1.0
        if "result" not in box and "err" not in box:
            raise RuntimeError("call never completed")
        if box.get("err") is not None:
            err = box["err"]
            raise err if isinstance(err, BaseException) \
                else RuntimeError(err)
        return box["result"]

    def stop_all(self) -> None:
        for cn in self.cluster_nodes.values():
            cn.stop()


def _corpus(n: int) -> List[Dict[str, Any]]:
    cats = ("a", "b", "c")
    return [{"op": "index", "id": f"md-{i}",
             "source": {"body": f"quick brown fox doc {i}",
                        "category": cats[i % 3],
                        "price": float((i * 7) % 100), "n": i}}
            for i in range(n)]


def run_macro(seed: int = 0, smoke: bool = False,
              root: Optional[str] = None) -> Dict[str, Any]:
    """Run the macro workload; returns the replay-stable summary dict
    (includes the full ``transcript`` — BENCH pops it and banks the
    sha256 instead)."""
    import tempfile
    if root is None:
        with tempfile.TemporaryDirectory() as tmp:
            return run_macro(seed=seed, smoke=smoke, root=tmp)

    rounds = 2 if smoke else 6
    round_s = 15.0
    horizon = rounds * round_s
    per_round = ({"interactive": 5, "aggs": 2, "bulk": 2,
                  "scroll": 1, "async": 1} if smoke else
                 {"interactive": 6, "aggs": 2, "bulk": 3,
                  "scroll": 1, "async": 1})
    bulk_batch = 6 if smoke else 10
    corpus_n = 24 if smoke else 90

    rng = random.Random(seed)
    cluster = _MacroCluster(3, root, seed)
    queue = cluster.queue
    try:
        master = cluster.stabilise(60)
        # setup runs under the reserved `_default` class so the
        # measured per-class tables hold ONLY the scheduled mix
        with _telectx.activate_workload_class("_default"):
            cluster.call(master.create_index, "md",
                         number_of_shards=2, number_of_replicas=1,
                         mappings=_DOCS_MAPPINGS)
            cluster.call(master.create_index, "mb",
                         number_of_shards=2, number_of_replicas=1,
                         settings={"index.tenant.default": "ingest"})
            cluster.run_for(30)
            seed_resp = cluster.call(master.bulk, "md",
                                     _corpus(corpus_n))
            assert seed_resp["errors"] == [], seed_resp
            cluster.call(master.refresh)
            # baseline report lays the history-ring sample the final
            # report's windowed deltas anchor against
            cluster.call(master.health_report)

        t0 = queue.now()
        transcript: List[Dict[str, Any]] = []
        disruptions: List[Dict[str, Any]] = []
        pending = [0]
        acked_ids: set = set()

        def begin(wclass: str, op: str, tenant: Optional[str]):
            row: Dict[str, Any] = {
                "t_s": round(queue.now() - t0, 3),
                "class": wclass, "op": op}
            if tenant is not None:
                row["tenant"] = tenant
            row["_start"] = queue.now()
            pending[0] += 1
            return row

        def finish(row: Dict[str, Any], err) -> None:
            pending[0] -= 1
            row["took_ms"] = round(
                (queue.now() - row.pop("_start")) * 1000.0, 3)
            row["ok"] = err is None
            transcript.append(row)

        def coord(k: int):
            ids = cluster.live_ids()
            return cluster.cluster_nodes[ids[k % len(ids)]]

        # ---- open-loop issue thunks (one per class) ------------------
        # searches coordinate on the stable master so ONE node's
        # windowed table crosses the workload_slo requests floor (the
        # indicator reads per-node windows); bulks rotate coordinators
        # so the /_workload/stats fan-out merges a real multi-node table

        def issue_interactive(tenant: str, variant: int):
            def fire():
                row = begin("interactive", "search", tenant)
                body = dict(_INTERACTIVE_BODIES[
                    variant % len(_INTERACTIVE_BODIES)])
                body["tenant"] = tenant
                master.search("md", body,
                              on_done=lambda r, e=None: finish(row, e))
            return fire

        def issue_aggs(tenant: str):
            def fire():
                row = begin("aggs", "aggs", tenant)
                body = dict(_AGGS_BODY)
                body["tenant"] = tenant
                master.search("md", body,
                              on_done=lambda r, e=None: finish(row, e))
            return fire

        def issue_bulk(k: int, rnd: int, j: int):
            def fire():
                row = begin("bulk", "bulk", "ingest")
                ids = [f"mb-{rnd}-{j}-{i}" for i in range(bulk_batch)]
                items = [{"op": "index", "id": did,
                          "source": {"body": f"ingest doc {did}",
                                     "n": i}}
                         for i, did in enumerate(ids)]

                def done(r, e=None):
                    if e is None and r:
                        for i, it in enumerate(r.get("items", [])):
                            if it and "error" not in it:
                                acked_ids.add(ids[i])
                    finish(row, e)

                coord(k).bulk("mb", items, on_done=done)
            return fire

        def issue_scroll(tenant: str):
            # drains run through the stable master coordinator: the
            # cursor record lives on the node that opened it
            def fire():
                row = begin("scroll", "scroll_drain", tenant)
                row["pages"] = 0

                def on_page(r, e=None):
                    if e is not None or not r["hits"]["hits"]:
                        finish(row, e)
                        return
                    row["pages"] += 1
                    master.scroll(r["_scroll_id"], 60.0,
                                  on_done=on_page)

                master.search(
                    "md", {"tenant": tenant,
                           "query": {"match_all": {}}, "size": 10},
                    on_done=on_page, scroll=60.0)
            return fire

        def issue_async(tenant: str, variant: int):
            def fire():
                row = begin("async", "async_submit", tenant)
                body = dict(_INTERACTIVE_BODIES[
                    variant % len(_INTERACTIVE_BODIES)])
                body["tenant"] = tenant

                def on_sub(r, e=None):
                    finish(row, e)
                    sid = (r or {}).get("id")
                    if not sid:
                        return
                    srow = begin("async", "async_status", tenant)

                    def on_get(r2, e2=None):
                        finish(srow, e2)

                    queue.schedule(
                        2.0, lambda: master.get_async_search(
                            sid, None, on_done=on_get),
                        f"macro async status [{sid}]")

                master.submit_async_search("md", body, None,
                                           on_done=on_sub)
            return fire

        # ---- chaos thunks -------------------------------------------

        bounce = {"node": None}

        def fire_reroute():
            state = master.state
            copies = [s for s in state.routing_table.all_shards()
                      if s.index == "md" and s.shard_id == 0
                      and s.current_node_id]
            src = next((s.current_node_id for s in copies if s.primary),
                       None)
            holders = {s.current_node_id for s in copies}
            free = sorted(set(cluster.live_ids()) - holders)
            entry = {"t_s": round(queue.now() - t0, 3),
                     "event": "reroute", "index": "md", "shard": 0,
                     "from": src, "to": free[0] if free else None,
                     "acked": False}
            disruptions.append(entry)
            if src is None or not free:
                return

            def done(r, e=None):
                entry["acked"] = e is None

            master.reroute(commands=[{"move": {
                "index": "md", "shard": 0,
                "from_node": src, "to_node": free[0]}}], on_done=done)

        def fire_stop():
            victims = [i for i in cluster.live_ids()
                       if i != master.local_node.node_id]
            if not victims:
                return
            bounce["node"] = victims[0]
            disruptions.append({"t_s": round(queue.now() - t0, 3),
                                "event": "node_stop",
                                "node": bounce["node"]})
            cluster.stop_node(bounce["node"])

        def fire_restart():
            if bounce["node"] is None:
                return
            disruptions.append({"t_s": round(queue.now() - t0, 3),
                                "event": "node_restart",
                                "node": bounce["node"]})
            cluster.restart_node(bounce["node"])

        # ring anchor: a report between the reroute and the node stop
        # lays the history sample the probe's 60s window anchors
        # against (the ring samples on report boundaries only)
        def fire_anchor():
            pending[0] += 1

            def done(r, e=None):
                pending[0] -= 1

            master.health_report(on_done=done)

        # mid-run async health probe: catches workload_slo while the
        # chaos-window burn is still inside the indicator's window
        slo_mid: Dict[str, Any] = {"status": None, "named": []}

        def fire_probe():
            pending[0] += 1

            def done(r, e=None):
                pending[0] -= 1
                if e is None:
                    ind = r["indicators"].get("workload_slo", {})
                    slo_mid["t_s"] = round(queue.now() - t0, 3)
                    slo_mid["status"] = ind.get("status")
                    slo_mid["named"] = sorted({
                        res for d in ind.get("diagnosis", [])
                        for res in d.get("affected_resources", [])})

            master.health_report(on_done=done)

        # ---- build the arrival schedule (all randomness up front) ----

        events: List[Any] = []
        seq = 0
        for rnd in range(rounds):
            base = rnd * round_s
            for _ in range(per_round["interactive"]):
                events.append((base + rng.uniform(0, round_s), seq,
                               issue_interactive(rng.choice(TENANTS),
                                                 seq)))
                seq += 1
            for _ in range(per_round["aggs"]):
                events.append((base + rng.uniform(0, round_s), seq,
                               issue_aggs(rng.choice(TENANTS))))
                seq += 1
            for j in range(per_round["bulk"]):
                events.append((base + rng.uniform(0, round_s), seq,
                               issue_bulk(seq, rnd, j)))
                seq += 1
            for _ in range(per_round["scroll"]):
                events.append((base + rng.uniform(0, round_s), seq,
                               issue_scroll(rng.choice(TENANTS))))
                seq += 1
            for _ in range(per_round["async"]):
                events.append((base + rng.uniform(0, round_s), seq,
                               issue_async(rng.choice(TENANTS), seq)))
                seq += 1
        events.append((0.35 * horizon, seq, fire_reroute))
        events.append((0.55 * horizon, seq + 1, fire_stop))
        events.append((0.75 * horizon, seq + 2, fire_restart))
        events.append((0.45 * horizon, seq + 3, fire_anchor))
        events.append((0.90 * horizon, seq + 4, fire_probe))
        events.sort(key=lambda e: (e[0], e[1]))

        # ---- drive --------------------------------------------------

        for t_arr, _, fire in events:
            dt = (t0 + t_arr) - queue.now()
            if dt > 0:
                queue.run_for(dt)
            fire()
        drained = False
        for _ in range(240):
            if pending[0] == 0:
                drained = True
                break
            queue.run_for(1.0)
        workload_virtual_s = max(horizon, 1e-9)

        # ---- verify + report (back to closed loop) ------------------

        with _telectx.activate_workload_class("_default"):
            cluster.run_for(60)  # let recovery/re-replication settle
            cluster.call(master.refresh)
            found = cluster.call(
                master.search, "mb",
                {"query": {"match_all": {}},
                 "size": 0})["hits"]["total"]["value"]
            cluster.run_for(11)  # ring boundary before the report
            report = cluster.call(master.health_report)
            merged = cluster.call(master.workload_stats)

        slo_ind = report["indicators"].get("workload_slo", {})
        classes_out: Dict[str, Any] = {}
        for c in sorted(merged["classes"]):
            if c.startswith("_"):
                continue
            e = merged["classes"][c]
            ops = sum(1 for r in transcript if r["class"] == c)
            classes_out[c] = {
                "ops": ops,
                "qps_virtual": round(ops / workload_virtual_s, 3),
                "searches": e["search"]["count"],
                "failed": e["search"]["failed"],
                "p50_ms": e["search"]["latency"]["p50_ms"],
                "p99_ms": e["search"]["latency"]["p99_ms"],
                "slo_objective_ms": e["slo"]["objective_ms"],
                "slo_violations": e["slo"]["violations"],
                "slo_burn_pct": e["slo"]["budget_burn_pct"],
                "indexing_bytes": e["indexing"]["bytes"],
                "rejections": e["indexing"]["rejections"],
            }
        transcript_blob = json.dumps(transcript, sort_keys=True)
        return {
            "seed": seed,
            "smoke": bool(smoke),
            "rounds": rounds,
            "horizon_virtual_s": horizon,
            "requests_issued": len(events) - 5,
            "requests_completed": len(transcript),
            "drained": drained,
            "classes": classes_out,
            "acked_writes": len(acked_ids),
            "docs_found": found,
            "acked_write_loss": max(0, len(acked_ids) - found),
            "disruptions": disruptions,
            "workload_slo": {
                "status": slo_ind.get("status"),
                "named": sorted({
                    r for d in slo_ind.get("diagnosis", [])
                    for r in d.get("affected_resources", [])}),
            },
            "workload_slo_mid": slo_mid,
            "workload_cardinality": merged["cardinality"],
            "transcript_rows": len(transcript),
            "transcript_sha256": hashlib.sha256(
                transcript_blob.encode()).hexdigest(),
            "transcript": transcript,
        }
    finally:
        cluster.stop_all()
