"""Sharded search execution over a device mesh.

The TPU-native replacement for the reference's scatter-gather protocol
(ref: SURVEY.md §2.3 — an index = N shards, every query fans out to all
shards and the coordinator merges per-shard top-k via
SearchPhaseController.mergeTopDocs / QueryPhaseResultConsumer incremental
reduce). Here the fan-out/merge is a single SPMD program over a
``jax.sharding.Mesh``:

- axis ``"shard"`` — partitions the corpus (postings blocks, doc lengths,
  live masks, vector slabs). The data-parallel axis of a search engine.
- axis ``"replica"`` — partitions the *query batch* (read scaling, the
  replica-count analogue). No communication crosses this axis.

Per device: score local blocks → local top-k; then ONE
``all_gather`` over the shard axis + re-top-k replaces the coordinator's
incremental reduce — the merge rides ICI instead of RPC (BASELINE.json
north star: "TopScoreDocCollector's top-k merge replaced by collectives +
on-device partial sort").

Multi-host note: with a multi-host mesh these same collectives ride
ICI within a host and DCN across hosts — the jit program is unchanged;
only the Mesh changes (jax.sharding semantics).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticsearch_tpu.index.segment import BLOCK_SIZE
from elasticsearch_tpu.utils.jax_compat import shard_map


# int32 global-id ceiling: with x64 off, `ids + shard * nd` computes in
# int32 and jnp.int64 requests silently narrow (JAX warns and truncates).
# Past this, the merge runs host-side in real int64 instead (exact).
GID_INT32_LIMIT = 2 ** 31


def _gids_exceed_int32(index: "ShardedIndex") -> bool:
    if jax.config.jax_enable_x64:
        return False
    if index.n_shards * index.n_docs_padded < GID_INT32_LIMIT:
        return False
    import logging
    logging.getLogger(__name__).warning(
        "sharded merge: %d shards x %d padded docs >= 2^31 with x64 "
        "disabled — global ids would wrap in int32; falling back to the "
        "host-side int64 merge", index.n_shards, index.n_docs_padded)
    return True


def _host_merge_topk(vals: np.ndarray, ids: np.ndarray, nd: int, k: int):
    """Merge per-shard local top-k [S, Q, k] host-side with exact int64
    global ids (the overflow-safe replacement for the on-device
    all_gather merge)."""
    s, q, kk = vals.shape
    gids = ids.astype(np.int64) + \
        (np.arange(s, dtype=np.int64)[:, None, None] * np.int64(nd))
    vv = vals.transpose(1, 0, 2).reshape(q, s * kk)
    gg = gids.transpose(1, 0, 2).reshape(q, s * kk)
    order = np.argsort(-vv, axis=1, kind="stable")[:, :k]
    return (np.take_along_axis(vv, order, axis=1),
            np.take_along_axis(gg, order, axis=1))


def _local_bm25_topk_all_shards(index: "ShardedIndex", sel_blocks,
                                sel_weights, k, k1, b):
    """Per-shard local top-k [S, Q, k] with LOCAL ids (no global-id
    arithmetic on device)."""
    step = jax.vmap(partial(
        _shard_bm25_topk_local, nd=index.n_docs_padded,
        avg_len=index.avg_len, k1=k1, b=b, k=k))
    return step(index.block_docids, index.block_tfs, index.doc_lens,
                index.live, jnp.asarray(sel_blocks),
                jnp.asarray(sel_weights))


def _local_knn_topk_all_shards(index: "ShardedIndex", queries, k):
    q = jnp.asarray(queries)

    def one(vectors, live):
        scores = jnp.einsum("qd,nd->qn", q.astype(vectors.dtype),
                            vectors, preferred_element_type=jnp.float32)
        masked = jnp.where(live[None, :], scores, -jnp.inf)
        return jax.lax.top_k(masked, k)

    return jax.vmap(one)(index.vectors, index.live)


def make_mesh(n_shards: Optional[int] = None, n_replicas: int = 1,
              devices=None) -> Mesh:
    """A ("replica", "shard") mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards is None:
        n_shards = len(devices) // n_replicas
    grid = np.array(devices[: n_replicas * n_shards]).reshape(
        n_replicas, n_shards)
    return Mesh(grid, ("replica", "shard"))


class ShardedIndex:
    """Corpus state laid out for a mesh: every per-shard array stacked on a
    leading shard axis and device_put with the shard-axis sharding.

    Built from per-shard (postings-style) numpy arrays padded to a common
    shape. The stacked arrays live distributed — each device holds only its
    own shard's slice (the HBM analogue of one Lucene shard per node).
    """

    def __init__(self, mesh: Mesh,
                 block_docids: np.ndarray,   # [S, TB, B] int32
                 block_tfs: np.ndarray,      # [S, TB, B] float32
                 doc_lens: np.ndarray,       # [S, ND] float32
                 live: np.ndarray,           # [S, ND] bool
                 avg_len: float,
                 vectors: Optional[np.ndarray] = None,  # [S, ND, D]
                 ):
        self.mesh = mesh
        shard_spec = NamedSharding(mesh, P("shard"))
        self.block_docids = jax.device_put(block_docids, shard_spec)
        self.block_tfs = jax.device_put(block_tfs, shard_spec)
        self.doc_lens = jax.device_put(doc_lens, shard_spec)
        self.live = jax.device_put(live, shard_spec)
        self.avg_len = float(avg_len)
        self.vectors = (jax.device_put(vectors, shard_spec)
                        if vectors is not None else None)
        self.n_shards = block_docids.shape[0]
        self.n_docs_padded = doc_lens.shape[1]


def sharded_bm25_topk(index: ShardedIndex,
                      sel_blocks: np.ndarray,    # [S, Q, NB] int32 per shard
                      sel_weights: np.ndarray,   # [S, Q, NB] float32
                      k: int, k1: float = 1.2, b: float = 0.75):
    """Batched sharded BM25 top-k: every shard scores its local postings
    for all Q queries, local top-k, all-gather + merge over the shard axis.

    Returns (scores [Q, k], global_docids [Q, k]) where global docid =
    shard_idx * n_docs_padded + local docid. Results replicated.
    """
    if _gids_exceed_int32(index):
        vals, ids = _local_bm25_topk_all_shards(
            index, sel_blocks, sel_weights, k, k1, b)
        return _host_merge_topk(np.asarray(vals), np.asarray(ids),
                                index.n_docs_padded, k)
    mesh = index.mesh
    nd = index.n_docs_padded

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                       P("shard", "replica"), P("shard", "replica")),
             out_specs=(P("replica"), P("replica")))
    def step(docids, tfs, lens, live, sel, ws):
        # corpus varies over "shard"; the query batch (dim 1 of sel/ws)
        # splits over "replica" — read scaling with zero cross-replica comm
        # leading shard axis is size 1 inside the shard_map body
        docids, tfs, lens, live = docids[0], tfs[0], lens[0], live[0]
        sel, ws = sel[0], ws[0]

        vals, ids = _shard_bm25_topk_local(
            docids, tfs, lens, live, sel, ws, nd, index.avg_len,
            k1, b, k)                                       # [Q, k]
        shard_idx = jax.lax.axis_index("shard")
        # global ids widen to int64 only under x64 (shard*nd can pass
        # 2^31 at many-shard scale); x64-off deployments stay int32 —
        # requesting int64 there just truncates with a warning
        gid_t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        gids = ids.astype(gid_t) + shard_idx.astype(gid_t) * nd
        # merge across shards: all_gather over ICI, re-top-k on device
        return _merge_over_shards(vals, gids, k)

    return step(index.block_docids, index.block_tfs, index.doc_lens,
                index.live, jnp.asarray(sel_blocks), jnp.asarray(sel_weights))


def sharded_knn_topk(index: ShardedIndex,
                     queries: np.ndarray,   # [Q, D] float32
                     k: int):
    """Sharded brute-force kNN: queries replicated, vector slab sharded
    over "shard" — per-shard MXU matmul + local top-k + all-gather merge
    (the dense analogue of the per-shard query phase)."""
    if _gids_exceed_int32(index):
        vals, ids = _local_knn_topk_all_shards(index, queries, k)
        return _host_merge_topk(np.asarray(vals), np.asarray(ids),
                                index.n_docs_padded, k)
    mesh = index.mesh
    nd = index.n_docs_padded

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard"), P("replica")),
             out_specs=(P("replica"), P("replica")))
    def step(vectors, live, q):
        vectors, live = vectors[0], live[0]
        scores = jnp.einsum("qd,nd->qn", q.astype(vectors.dtype), vectors,
                            preferred_element_type=jnp.float32)
        masked = jnp.where(live[None, :], scores, -jnp.inf)
        vals, ids = jax.lax.top_k(masked, k)                 # [Q, k]
        shard_idx = jax.lax.axis_index("shard")
        # global ids widen to int64 only under x64 (shard*nd can pass
        # 2^31 at many-shard scale); x64-off deployments stay int32 —
        # requesting int64 there just truncates with a warning
        gid_t = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        gids = ids.astype(gid_t) + shard_idx.astype(gid_t) * nd
        all_vals = jax.lax.all_gather(vals, "shard", axis=1)
        all_gids = jax.lax.all_gather(gids, "shard", axis=1)
        qn = all_vals.shape[0]
        top_vals, top_idx = jax.lax.top_k(all_vals.reshape(qn, -1), k)
        top_gids = jnp.take_along_axis(all_gids.reshape(qn, -1), top_idx, axis=1)
        return top_vals, top_gids

    return step(index.vectors, index.live, jnp.asarray(queries))


def _shard_bm25_topk_local(docids, tfs, lens, live, sel, ws, nd,
                           avg_len, k1, b, k):
    """Per-shard batched BM25 local top-k [Q, k] (the shared body of the
    sharded BM25 and hybrid kernels)."""
    def score_one(sel_q, ws_q):
        d = jnp.take(docids, sel_q, axis=0)
        tf = jnp.take(tfs, sel_q, axis=0)
        dl = jnp.take(lens, d)
        norm = k1 * (1.0 - b + b * dl / avg_len)
        contrib = ws_q[:, None] * jnp.where(tf > 0, tf / (tf + norm), 0.0)
        scores = jnp.zeros(nd, jnp.float32).at[d.reshape(-1)].add(
            contrib.reshape(-1), mode="drop")
        masked = jnp.where(live & (scores > 0), scores, -jnp.inf)
        return jax.lax.top_k(masked, k)

    return jax.vmap(score_one)(sel, ws)


def _merge_over_shards(vals, gids, k):
    """all_gather over the shard axis + re-top-k (the on-device
    coordinator merge shared by every sharded kernel)."""
    av = jax.lax.all_gather(vals, "shard", axis=1)
    ag = jax.lax.all_gather(gids, "shard", axis=1)
    q = av.shape[0]
    tv, ti = jax.lax.top_k(av.reshape(q, -1), k)
    return tv, jnp.take_along_axis(ag.reshape(q, -1), ti, axis=1)


def sharded_hybrid_rrf(index: ShardedIndex,
                       sel_blocks: np.ndarray,    # [S, Q, NB] int32
                       sel_weights: np.ndarray,   # [S, Q, NB] float32
                       queries: np.ndarray,       # [Q, D] float32
                       k: int, k1: float = 1.2, b: float = 0.75,
                       rank_constant: int = 60):
    """Hybrid BM25 + kNN with reciprocal rank fusion, fully on-mesh
    (BASELINE.md config 5 at multi-chip scale): each shard scores both
    branches locally, the per-branch top-k merges over the shard axis
    via all_gather, and the RRF fusion — a segmented sum of 1/(c+rank)
    contributions keyed by global docid — reuses ops/bm25.py's
    segmented_topk (no host round-trips). The query batch splits over
    the replica axis like the sibling kernels (read scaling).

    Returns (rrf_scores [Q, k], global_docids [Q, k]), replica-sharded
    over Q."""
    from elasticsearch_tpu.ops.bm25 import segmented_topk

    if _gids_exceed_int32(index):
        # host fusion over the overflow-safe per-branch merges
        b_vals, b_gids = sharded_bm25_topk(index, sel_blocks,
                                           sel_weights, k, k1, b)
        v_vals, v_gids = sharded_knn_topk(index, queries, k)
        c = float(rank_constant)
        q_n = np.asarray(b_vals).shape[0]
        out_v = np.zeros((q_n, k), np.float32)
        out_g = np.zeros((q_n, k), np.int64)
        for qi in range(q_n):
            fused: Dict[int, float] = {}
            for vals, gids in ((np.asarray(b_vals)[qi],
                                np.asarray(b_gids)[qi]),
                               (np.asarray(v_vals)[qi],
                                np.asarray(v_gids)[qi])):
                for rank, (v, g) in enumerate(zip(vals, gids)):
                    if np.isfinite(v):
                        fused[int(g)] = fused.get(int(g), 0.0) + \
                            1.0 / (c + rank + 1.0)
            top = sorted(fused.items(), key=lambda e: (-e[1], e[0]))[:k]
            for j, (g, v) in enumerate(top):
                out_v[qi, j] = v
                out_g[qi, j] = g
        return out_v, out_g

    mesh = index.mesh
    nd = index.n_docs_padded
    c = float(rank_constant)

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                       P("shard"), P("shard", "replica"),
                       P("shard", "replica"), P("replica")),
             out_specs=(P("replica"), P("replica")))
    def step(docids, tfs, lens, live, vectors, sel, ws, qv):
        docids, tfs, lens, live = docids[0], tfs[0], lens[0], live[0]
        vectors = vectors[0]
        sel, ws = sel[0], ws[0]

        b_vals, b_ids = _shard_bm25_topk_local(
            docids, tfs, lens, live, sel, ws, nd, index.avg_len,
            k1, b, k)                                        # [Q, k]
        v_scores = jnp.einsum("qd,nd->qn", qv.astype(vectors.dtype),
                              vectors,
                              preferred_element_type=jnp.float32)
        v_masked = jnp.where(live[None, :], v_scores, -jnp.inf)
        v_vals, v_ids = jax.lax.top_k(v_masked, k)           # [Q, k]

        shard_idx = jax.lax.axis_index("shard")
        off = shard_idx.astype(jnp.int64) * nd
        b_gids = b_ids.astype(jnp.int64) + off
        v_gids = v_ids.astype(jnp.int64) + off

        gb_vals, gb_gids = _merge_over_shards(b_vals, b_gids, k)
        gv_vals, gv_gids = _merge_over_shards(v_vals, v_gids, k)

        # RRF contributions: 1/(c + rank + 1); empty slots contribute 0
        ranks = jnp.arange(k, dtype=jnp.float32)
        rc = 1.0 / (c + ranks + 1.0)

        def fuse_one(bg, bvals, vg, vvals):
            gids = jnp.concatenate([bg, vg])
            contrib = jnp.concatenate([
                jnp.where(jnp.isfinite(bvals), rc, 0.0),
                jnp.where(jnp.isfinite(vvals), rc, 0.0)])
            # dtype-safe sentinel: int64 narrows to int32 when x64 is off
            sentinel = jnp.asarray(jnp.iinfo(gids.dtype).max, gids.dtype)
            key = jnp.where(contrib > 0, gids, sentinel)
            return segmented_topk(key, contrib, k, sentinel)

        return jax.vmap(fuse_one)(gb_gids, gb_vals, gv_gids, gv_vals)

    return step(index.block_docids, index.block_tfs, index.doc_lens,
                index.live, index.vectors, jnp.asarray(sel_blocks),
                jnp.asarray(sel_weights), jnp.asarray(queries))


def sharded_dfs_stats(index: ShardedIndex,
                      sel_blocks: np.ndarray,   # [S, NB]
                      ) -> jax.Array:
    """The DFS phase analogue (ref: search/dfs/DfsPhase.java — all-shard
    term-statistics gather for consistent IDF): per-shard doc-freq counts
    psum'd over the shard axis."""
    mesh = index.mesh

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard")),
             out_specs=P())
    def step(tfs, sel):
        tfs, sel = tfs[0], sel[0]
        t = jnp.take(tfs, sel, axis=0)           # [NB, B]
        local_df = (t > 0).sum(axis=1)           # per selected block
        return jax.lax.psum(local_df, "shard")

    return step(index.block_tfs, jnp.asarray(sel_blocks))


def build_sharded_index(mesh: Mesh, segments: List, field: str,
                        with_vectors: Optional[str] = None) -> Tuple[ShardedIndex, List]:
    """Stack per-shard segments (padded to common shapes) into a
    ShardedIndex. segments: one Segment per shard (shards beyond
    len(segments) are empty)."""
    s = mesh.shape["shard"]
    pfs = [seg.postings.get(field) for seg in segments]
    max_tb = max((pf.block_docids.shape[0] for pf in pfs if pf is not None),
                 default=0) + 1  # +1 zero pad block
    max_nd = max((seg.n_docs for seg in segments), default=1)
    max_nd = ((max_nd + 1023) // 1024) * 1024

    block_docids = np.zeros((s, max_tb, BLOCK_SIZE), np.int32)
    block_tfs = np.zeros((s, max_tb, BLOCK_SIZE), np.float32)
    doc_lens = np.ones((s, max_nd), np.float32)
    live = np.zeros((s, max_nd), bool)
    total_len = 0.0
    total_docs = 0
    for i, seg in enumerate(segments[:s]):
        pf = seg.postings.get(field)
        if pf is None:
            continue
        tb = pf.block_docids.shape[0]
        block_docids[i, :tb] = pf.block_docids
        block_tfs[i, :tb] = pf.block_tfs
        doc_lens[i, : seg.n_docs] = np.maximum(pf.field_lengths, 1.0)
        live[i, : seg.n_docs] = seg.live
        total_len += pf.field_lengths.sum()
        total_docs += pf.doc_count

    vectors = None
    if with_vectors is not None:
        dims = next(seg.vectors[with_vectors].dims for seg in segments
                    if with_vectors in seg.vectors)
        vectors = np.zeros((s, max_nd, dims), np.float32)
        for i, seg in enumerate(segments[:s]):
            vv = seg.vectors.get(with_vectors)
            if vv is not None:
                from elasticsearch_tpu.ops.vector import prepare_vectors
                prepped, _ = prepare_vectors(vv.vectors, vv.similarity,
                                             np.float32)
                vectors[i, : len(prepped)] = prepped

    avg_len = total_len / max(1, total_docs)
    return ShardedIndex(mesh, block_docids, block_tfs, doc_lens, live,
                        avg_len, vectors), pfs
