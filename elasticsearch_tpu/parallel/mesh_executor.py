"""Mesh-sharded plan execution: REST `_search` → one SPMD program.

The integration the reference achieves with TransportSearchAction's
scatter-gather (ref: action/search/TransportSearchAction.java:93,469-523 —
per-shard RPC fan-out, SearchPhaseController.java:154-218 coordinator
merge): on a TPU mesh the same multi-shard query runs as ONE
``shard_map`` program — every device scores its shard's postings with the
fused plan kernel (ops/plan.py plan_topk_body), then a single
``all_gather`` over the shard axis + on-device re-top-k replaces the
coordinator merge, and a ``psum`` replaces the total-hits accumulation.
The merge rides ICI instead of RPC.

Per-shard differences the RPC path exhibits are preserved exactly:
term weights (idf) and keyword constants come from each shard's own
statistics (ES default per-shard IDF; dfs_query_then_fetch would psum
the stats first — sharded_dfs_stats in parallel/sharded.py), so a mesh
search returns byte-identical results to the per-shard loop it replaces.

Corpus residency: per (index, shards-epoch) the per-shard postings stack
onto a leading shard axis and ``device_put`` with a ``P("shard")``
sharding — each device holds only its shard, the HBM analogue of one
Lucene shard per data node. Multi-host meshes run the identical program;
only the Mesh changes (collectives ride ICI in-host, DCN across hosts).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticsearch_tpu.index.segment import BLOCK_SIZE, Segment
from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.ops.device import block_bucket
from elasticsearch_tpu.search.plan import LogicalPlan, compile_plan

DOC_PAD = 1024


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class _CompositePostings:
    """A shard's postings for one field, across MULTIPLE segments,
    presented as one block layout: block arrays concatenate (docids
    offset by each segment's doc base — padding entries keep tf=0, so
    every kernel's tf>0 guard ignores their shifted docids), term
    lookups return one RANGE PER SUB-SEGMENT."""

    def __init__(self, pfs: List, doc_bases: List[int],
                 n_docs_total: int):
        present = [(j, pf) for j, pf in enumerate(pfs) if pf is not None]
        self._block_offsets = {}
        docids, tfs = [], []
        off = 0
        for j, pf in present:
            docids.append(pf.block_docids + np.int32(doc_bases[j]))
            tfs.append(pf.block_tfs)
            self._block_offsets[j] = off
            off += pf.block_docids.shape[0]
        self.block_docids = (np.concatenate(docids) if docids
                             else np.zeros((0, BLOCK_SIZE), np.int32))
        self.block_tfs = (np.concatenate(tfs) if tfs
                          else np.zeros((0, BLOCK_SIZE), np.float32))
        lens = np.ones(n_docs_total, np.float32)
        sum_ttf = 0
        doc_count = 0
        for j, pf in present:
            nd = len(pf.field_lengths)
            lens[doc_bases[j]: doc_bases[j] + nd] = pf.field_lengths
            sum_ttf += pf.sum_total_term_freq
            doc_count += pf.doc_count
        self.field_lengths = lens
        self.avg_field_length = sum_ttf / max(1, doc_count)
        self._pfs = present

    def term_id(self, term: str) -> int:
        # 0/-1 presence flag: block ranges come from term_block_ranges
        for _j, pf in self._pfs:
            if pf.term_id(term) >= 0:
                return 0
        return -1

    def term_block_ranges(self, term: str) -> List[Tuple[int, int]]:
        out = []
        for j, pf in self._pfs:
            tid = pf.term_id(term)
            if tid >= 0:
                out.append((self._block_offsets[j]
                            + int(pf.term_block_start[tid]),
                            int(pf.term_block_count[tid])))
        return out


class _CompositeShard:
    """Multiple segments of one shard presented as a single
    segment-like object for the mesh corpus (the per-device analogue of
    stacking a shard's segments into one resident layout; ref:
    TransportSearchAction fans out per shard, not per segment)."""

    def __init__(self, segments: List[Segment]):
        self.sub_segments = segments
        self.name = "+".join(seg.name for seg in segments)
        self.doc_bases = []
        total = 0
        for seg in segments:
            self.doc_bases.append(total)
            total += seg.n_docs
        self.n_docs = total
        self.postings = _CompositePostingsMap(self)

    @property
    def live(self) -> np.ndarray:
        return np.concatenate([seg.live for seg in self.sub_segments]) \
            if self.sub_segments else np.zeros(0, bool)

    @property
    def live_version(self):
        return tuple(seg.live_version for seg in self.sub_segments)

    def locate(self, docid: int) -> Tuple[int, int]:
        """composite docid → (segment_idx, local_docid)."""
        import bisect
        j = bisect.bisect_right(self.doc_bases, docid) - 1
        return j, docid - self.doc_bases[j]


class _CompositePostingsMap:
    def __init__(self, shard: _CompositeShard):
        self._shard = shard
        self._cache: Dict[str, Optional[_CompositePostings]] = {}

    def get(self, name: str):
        if name not in self._cache:
            pfs = [seg.postings.get(name)
                   for seg in self._shard.sub_segments]
            self._cache[name] = (
                _CompositePostings(pfs, self._shard.doc_bases,
                                   self._shard.n_docs)
                if any(pf is not None for pf in pfs) else None)
        return self._cache[name]


def _term_ranges(pf, term: str) -> List[Tuple[int, int]]:
    """Block ranges for a term — one per sub-segment on composites,
    a single contiguous range on plain PostingsFields."""
    ranges = getattr(pf, "term_block_ranges", None)
    if ranges is not None:
        return ranges(term)
    tid = pf.term_id(term)
    if tid < 0:
        return []
    return [(int(pf.term_block_start[tid]),
             int(pf.term_block_count[tid]))]


class MeshFieldState:
    """One field's postings stacked over shards, device-sharded."""

    def __init__(self, mesh: Mesh, pfs: List, n_docs_padded: int):
        s = len(pfs)
        tb_max = max((pf.block_docids.shape[0] for pf in pfs if pf is not None),
                     default=0)
        docids = np.zeros((s, tb_max + 1, BLOCK_SIZE), np.int32)
        tfs = np.zeros((s, tb_max + 1, BLOCK_SIZE), np.float32)
        lens = np.ones((s, n_docs_padded), np.float32)
        for i, pf in enumerate(pfs):
            if pf is None:
                continue
            tb = pf.block_docids.shape[0]
            docids[i, :tb] = pf.block_docids
            tfs[i, :tb] = pf.block_tfs
            nd = len(pf.field_lengths)
            lens[i, :nd] = np.maximum(pf.field_lengths, 1.0)
            lens[i, nd:] = max(float(pf.avg_field_length), 1.0)
        # leading axis is the shard axis; shard_map slices it per device
        shard_spec = NamedSharding(mesh, P("shard"))
        self.block_docids = jax.device_put(docids, shard_spec)
        self.block_tfs = jax.device_put(tfs, shard_spec)
        self.doc_lens = jax.device_put(lens, shard_spec)
        self.zero_block = tb_max      # common reserved all-zeros block row
        self.pfs = pfs                # host term dicts for binding


class MeshCorpus:
    """A multi-shard index resident on a device mesh (one shard per
    device), built lazily per field from each shard's single segment."""

    def __init__(self, mesh: Mesh, segments: List[Segment]):
        self.mesh = mesh
        self.segments = segments
        self.n_shards = len(segments)
        nd = max((seg.n_docs for seg in segments), default=1)
        self.n_docs_padded = max(DOC_PAD, _round_up(nd, DOC_PAD))
        self.live_versions: Tuple[int, ...] = ()
        self.live = None
        self.refresh_live()
        self._fields: Dict[str, MeshFieldState] = {}

    def refresh_live(self) -> None:
        """Deletes touch only the live bitmaps — re-upload just those
        (postings are immutable per segment, like the per-shard device
        cache's live-only refresh, search/context.py)."""
        versions = tuple(seg.live_version for seg in self.segments)
        if self.live is not None and versions == self.live_versions:
            return
        live = np.zeros((self.n_shards, self.n_docs_padded), bool)
        for i, seg in enumerate(self.segments):
            live[i, : seg.n_docs] = seg.live
        self.live = jax.device_put(
            live, NamedSharding(self.mesh, P("shard")))
        self.live_versions = versions

    def field(self, name: str) -> Optional[MeshFieldState]:
        if name not in self._fields:
            pfs = [seg.postings.get(name) for seg in self.segments]
            if all(pf is None for pf in pfs):
                return None
            self._fields[name] = MeshFieldState(
                self.mesh, pfs, self.n_docs_padded)
        return self._fields[name]


def plans_mesh_compatible(plans: List[LogicalPlan]) -> bool:
    """All shards compiled the same query to the same structure with no
    dense factors (dense columns are not mesh-resident yet)."""
    if any(p is None for p in plans):
        return False
    p0 = plans[0]
    if any(p.dense for p in plans):
        return False
    for p in plans[1:]:
        if (len(p.groups) != len(p0.groups) or p.combine != p0.combine
                or p.msm != p0.msm or p.n_must != p0.n_must
                or p.n_filter != p0.n_filter):
            return False
    return True


def bind_mesh(corpus: MeshCorpus, plans: List[LogicalPlan]):
    """Bind one LogicalPlan per shard (weights/consts carry each shard's
    own idf) into stacked [S, ...] selection + group arrays. Returns None
    when a referenced field has no postings anywhere."""
    s = corpus.n_shards
    p0 = plans[0]
    ngroups = len(p0.groups)

    field_names: List[str] = []
    seen = set()
    for g in p0.groups:
        for t in g.terms:
            if t.field not in seen:
                seen.add(t.field)
                field_names.append(t.field)

    per_field_sel: Dict[str, List[Tuple[list, list, list, list, list]]] = {}
    for fname in field_names:
        fs = corpus.field(fname)
        if fs is None:
            continue
        shard_sels = []
        for si in range(s):
            pf = fs.pfs[si]
            ids: List[int] = []
            grps: List[int] = []
            subs: List[int] = []
            ws: List[float] = []
            consts: List[bool] = []
            if pf is not None:
                for gi, g in enumerate(plans[si].groups):
                    for t in g.terms:
                        if t.field != fname:
                            continue
                        for start, count in _term_ranges(pf, t.term):
                            ids.extend(range(start, start + count))
                            grps.extend([gi] * count)
                            subs.extend([t.sub] * count)
                            ws.extend([t.weight] * count)
                            consts.extend([t.const] * count)
            shard_sels.append((ids, grps, subs, ws, consts))
        per_field_sel[fname] = shard_sels

    if not per_field_sel:
        return None

    streams = []
    shard_spec = NamedSharding(corpus.mesh, P("shard"))
    for fname, shard_sels in per_field_sel.items():
        fs = corpus.field(fname)
        nb = block_bucket(max(1, max(len(e[0]) for e in shard_sels)))
        sel = np.full((s, nb), fs.zero_block, np.int32)
        grp = np.full((s, nb), ngroups, np.int32)
        sub = np.zeros((s, nb), np.int32)
        w = np.zeros((s, nb), np.float32)
        cst = np.zeros((s, nb), bool)
        avg = np.ones(s, np.float32)
        for si, (ids, grps, subs, ws, consts) in enumerate(shard_sels):
            n = len(ids)
            sel[si, :n] = ids
            grp[si, :n] = grps
            sub[si, :n] = subs
            w[si, :n] = ws
            cst[si, :n] = consts
            pf = fs.pfs[si]
            if pf is not None:
                avg[si] = max(float(pf.avg_field_length), 1.0)
        streams.append(plan_ops.FieldStream(
            fs.block_docids, fs.block_tfs, fs.doc_lens,
            jax.device_put(avg, shard_spec),
            jax.device_put(sel, shard_spec),
            jax.device_put(grp, shard_spec),
            jax.device_put(sub, shard_spec),
            jax.device_put(w, shard_spec),
            jax.device_put(cst, shard_spec)))

    gpad = max(4, block_bucket(max(1, ngroups)))
    kind = np.full((s, gpad), plan_ops.FILTER, np.int32)
    req = np.full((s, gpad), 1 << 30, np.int32)
    const = np.full((s, gpad), np.nan, np.float32)
    for si, p in enumerate(plans):
        for gi, g in enumerate(p.groups):
            kind[si, gi] = g.kind
            req[si, gi] = g.req
            const[si, gi] = g.const_score
    bonus = np.asarray([p.bonus for p in plans], np.float32)
    return (streams,
            jax.device_put(kind, shard_spec),
            jax.device_put(req, shard_spec),
            jax.device_put(const, shard_spec),
            jax.device_put(bonus, shard_spec))


@partial(jax.jit,
         static_argnames=("mesh", "k", "combine", "k1", "b",
                          "n_must", "n_filter", "msm", "tie", "nd"))
def _sharded_plan_step(streams, group_kind, group_req, group_const, bonus,
                       live, mesh: Mesh, nd: int,
                       n_must: int, n_filter: int, msm: int, tie: float,
                       k1: float, b: float, k: int, combine: str):
    in_specs = (tuple(plan_ops.FieldStream(*([P("shard")] * 9))
                      for _ in streams),
                P("shard"), P("shard"), P("shard"), P("shard"), P("shard"))

    @partial(jax.shard_map, mesh=mesh, check_vma=False,
             in_specs=in_specs, out_specs=P())
    def step(sts, gk, gr, gc, bo, lv):
        local = tuple(
            plan_ops.FieldStream(st.block_docids[0], st.block_tfs[0],
                                 st.doc_lens[0], st.avg_len[0],
                                 st.sel_blocks[0], st.sel_group[0],
                                 st.sel_sub[0], st.sel_weight[0],
                                 st.sel_const[0])
            for st in sts)
        vals, ids, total = plan_ops.plan_topk_body(
            local, gk[0], gr[0], gc[0], lv[0], jnp.ones(1, bool),
            jnp.int32(n_must), jnp.int32(n_filter), jnp.int32(msm),
            bo[0], jnp.float32(tie), jnp.float32(0.0),
            k1, b, k, combine, False, False)
        shard_idx = jax.lax.axis_index("shard").astype(jnp.int32)
        gids = jnp.where(ids == plan_ops._SENTINEL, plan_ops._SENTINEL,
                         ids + shard_idx * nd)
        # ONE all_gather over ICI + on-device re-top-k = coordinator merge
        av = jax.lax.all_gather(vals, "shard")        # [S, k]
        ag = jax.lax.all_gather(gids, "shard")
        tv, ti = jax.lax.top_k(av.reshape(-1), k)
        tg = jnp.take(ag.reshape(-1), ti)
        tg = jnp.where(tv > -jnp.inf, tg, plan_ops._SENTINEL)
        # pack → one readback for the whole mesh query
        return plan_ops.pack_result(tv, tg, jax.lax.psum(total, "shard"))

    return step(tuple(streams), group_kind, group_req, group_const,
                bonus, live)


class MeshSearchExecutor:
    """Service-side entry: caches MeshCorpus per shard-set epoch and runs
    compatible multi-shard queries as one SPMD launch."""

    def __init__(self, max_cached: int = 4):
        self._cache: Dict[tuple, MeshCorpus] = {}
        self._cache_lock = threading.Lock()
        self._max_cached = max_cached
        self.mesh_searches = 0   # stat: queries served via the mesh

    @staticmethod
    def available_devices() -> int:
        return len(jax.devices())

    def corpus_for(self, index_name: str,
                   shard_segments: List[Segment]) -> MeshCorpus:
        # keyed by segment NAMES (postings identity); deletes only bump
        # live_version and refresh the live bitmaps in place
        key = (index_name, tuple(seg.name for seg in shard_segments))
        with self._cache_lock:
            corpus = self._cache.get(key)
            if corpus is None:
                from elasticsearch_tpu.parallel.sharded import make_mesh
                mesh = make_mesh(n_shards=len(shard_segments))
                corpus = MeshCorpus(mesh, shard_segments)
                while len(self._cache) >= self._max_cached:
                    self._cache.pop(next(iter(self._cache)))
                self._cache[key] = corpus
            else:
                corpus.segments = shard_segments
                corpus.refresh_live()
        return corpus

    def execute(self, index_name: str, searchers, query,
                k: int) -> Optional[Tuple[list, int]]:
        """Try the mesh path: searchers = the index's per-shard
        ShardSearchers (each must hold exactly one segment). Returns
        ([(shard_idx, local_docid, score)], total) sorted by (-score,
        shard, docid), or None to fall back to the per-shard loop."""
        n_shards = len(searchers)
        if k < 1:
            return None   # size:0 — per-shard path keeps max_score semantics
        if n_shards < 2 or self.available_devices() < n_shards:
            return None
        if any(len(s.segments) == 0 for s in searchers):
            return None
        # probe shard 0 first: ineligible queries (dense factors, scripts,
        # sorts…) bail after ONE compile instead of S
        first = compile_plan(query.rewrite(searchers[0]), searchers[0])
        if first is None or first.dense:
            return None
        plans = [first]
        for s in searchers[1:]:
            rq = query.rewrite(s)
            plans.append(compile_plan(rq, s))
        if not plans_mesh_compatible(plans):
            return None
        shard_views = [s.segments[0] if len(s.segments) == 1
                       else _CompositeShard(list(s.segments))
                       for s in searchers]
        # float-pack id overflow guard: the packed readback carries
        # GLOBAL ids (shard * nd_padded + docid) as float32 casts, exact
        # only < 2^24 — past that, fall back to the per-shard RPC merge
        # instead of silently corrupting low docid bits
        from elasticsearch_tpu.ops.plan import PACKED_ID_LIMIT
        nd_max = max((v.n_docs for v in shard_views), default=1)
        nd_padded = max(DOC_PAD, _round_up(nd_max, DOC_PAD))
        if n_shards * nd_padded >= PACKED_ID_LIMIT:
            import logging
            logging.getLogger(__name__).warning(
                "mesh fast path skipped: %d shards x %d padded docs "
                ">= 2^24 float-packed global-id ceiling; using the "
                "per-shard fallback", n_shards, nd_padded)
            return None
        corpus = self.corpus_for(index_name, shard_views)
        bound = bind_mesh(corpus, plans)
        if bound is None:
            self.mesh_searches += 1
            return [], 0   # no query term exists in any shard
        streams, gk, gr, gc, bo = bound
        p0 = plans[0]
        packed = _sharded_plan_step(
            streams, gk, gr, gc, bo, corpus.live, corpus.mesh,
            corpus.n_docs_padded, p0.n_must, p0.n_filter, p0.msm,
            float(p0.tie), float(searchers[0].k1), float(searchers[0].b),
            int(k), p0.combine)
        self.mesh_searches += 1
        vals, gids, total = plan_ops.unpack_result(np.asarray(packed),
                                                   int(k))
        nd = corpus.n_docs_padded
        docs = []
        for v, g in zip(vals, gids):
            if v <= -np.inf:
                continue
            shard, docid = int(g) // nd, int(g) % nd
            view = corpus.segments[shard]
            if isinstance(view, _CompositeShard):
                seg_idx, docid = view.locate(docid)
            else:
                seg_idx = 0
            docs.append((shard, seg_idx, docid, float(v)))
        return docs, int(total)
