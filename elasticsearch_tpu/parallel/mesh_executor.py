"""Mesh-sharded serving backend: REST `_search` → one SPMD program.

The integration the reference achieves with TransportSearchAction's
scatter-gather (ref: action/search/TransportSearchAction.java:93,469-523 —
per-shard RPC fan-out, SearchPhaseController.java:154-218 coordinator
merge): on a device mesh the same multi-shard query runs as ONE
``shard_map`` program — every device scores its shard's postings with the
fused plan kernel (ops/plan.py plan_topk_mesh), then a single
``all_gather`` over the shard axis + on-device re-top-k replaces the
coordinator merge, and a ``psum`` replaces the total-hits accumulation.
The merge rides ICI instead of RPC.

Per-shard differences the RPC path exhibits are preserved exactly:
term weights (idf) and keyword constants come from each shard's own
statistics (ES default per-shard IDF; dfs_query_then_fetch would psum
the stats first — sharded_dfs_stats in parallel/sharded.py), so a mesh
search returns byte-identical results to the per-shard loop it replaces.

Corpus residency: per (index, shards-epoch) the per-shard postings stack
onto a leading shard axis and ``device_put`` with a ``P("shard")``
sharding — each device holds only its shard, the HBM analogue of one
Lucene shard per data node. Multi-host meshes run the identical program;
only the Mesh changes (collectives ride ICI in-host, DCN across hosts).

:class:`MeshSearchBackend` is the serving entry: ``search/service.py``
dispatches eligible multi-shard queries to it (bm25/bool via the plan
kernel, pure kNN via the vector kernels below) and both
``search/batching.py`` and the native front (``search/fastpath.py``)
borrow its replica-axis helpers to fan query COHORTS across devices.
Every ineligible shape falls back to the per-shard loop with a typed
``fallback.<reason>`` counter — never an error — and the dispatch/
fallback/residency surface ships via ``GET /_kernels`` (rest/api.py).

Ceilings honored with clean fallback (see ops/plan.py / sharded.py):
``PACKED_ID_LIMIT`` (2^24: packed readback ids ride float32 casts) and
``GID_INT32_LIMIT`` (2^31: global-id arithmetic with x64 off — the
sharded kernel library falls back to a host int64 merge past it).
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticsearch_tpu.index.segment import BLOCK_SIZE, Segment
from elasticsearch_tpu.ops import plan as plan_ops
from elasticsearch_tpu.ops.device import block_bucket, readback
from elasticsearch_tpu.search.plan import LogicalPlan, compile_plan
from elasticsearch_tpu.telemetry.engine import tracked_jit
from elasticsearch_tpu.utils.jax_compat import shard_map

DOC_PAD = 1024


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class _CompositePostings:
    """A shard's postings for one field, across MULTIPLE segments,
    presented as one block layout: block arrays concatenate (docids
    offset by each segment's doc base — padding entries keep tf=0, so
    every kernel's tf>0 guard ignores their shifted docids), term
    lookups return one RANGE PER SUB-SEGMENT."""

    def __init__(self, pfs: List, doc_bases: List[int],
                 n_docs_total: int):
        present = [(j, pf) for j, pf in enumerate(pfs) if pf is not None]
        self._block_offsets = {}
        docids, tfs = [], []
        off = 0
        for j, pf in present:
            docids.append(pf.block_docids + np.int32(doc_bases[j]))
            tfs.append(pf.block_tfs)
            self._block_offsets[j] = off
            off += pf.block_docids.shape[0]
        self.block_docids = (np.concatenate(docids) if docids
                             else np.zeros((0, BLOCK_SIZE), np.int32))
        self.block_tfs = (np.concatenate(tfs) if tfs
                          else np.zeros((0, BLOCK_SIZE), np.float32))
        lens = np.ones(n_docs_total, np.float32)
        sum_ttf = 0
        doc_count = 0
        for j, pf in present:
            nd = len(pf.field_lengths)
            lens[doc_bases[j]: doc_bases[j] + nd] = pf.field_lengths
            sum_ttf += pf.sum_total_term_freq
            doc_count += pf.doc_count
        self.field_lengths = lens
        self.avg_field_length = sum_ttf / max(1, doc_count)
        self._pfs = present

    def term_id(self, term: str) -> int:
        # 0/-1 presence flag: block ranges come from term_block_ranges
        for _j, pf in self._pfs:
            if pf.term_id(term) >= 0:
                return 0
        return -1

    def term_block_ranges(self, term: str) -> List[Tuple[int, int]]:
        out = []
        for j, pf in self._pfs:
            tid = pf.term_id(term)
            if tid >= 0:
                out.append((self._block_offsets[j]
                            + int(pf.term_block_start[tid]),
                            int(pf.term_block_count[tid])))
        return out


class _CompositeShard:
    """Multiple segments of one shard presented as a single
    segment-like object for the mesh corpus (the per-device analogue of
    stacking a shard's segments into one resident layout; ref:
    TransportSearchAction fans out per shard, not per segment)."""

    def __init__(self, segments: List[Segment]):
        self.sub_segments = segments
        self.name = "+".join(seg.name for seg in segments)
        self.doc_bases = []
        total = 0
        for seg in segments:
            self.doc_bases.append(total)
            total += seg.n_docs
        self.n_docs = total
        self.postings = _CompositePostingsMap(self)

    @property
    def live(self) -> np.ndarray:
        return np.concatenate([seg.live for seg in self.sub_segments]) \
            if self.sub_segments else np.zeros(0, bool)

    @property
    def live_version(self):
        return tuple(seg.live_version for seg in self.sub_segments)

    def locate(self, docid: int) -> Tuple[int, int]:
        """composite docid → (segment_idx, local_docid)."""
        import bisect
        j = bisect.bisect_right(self.doc_bases, docid) - 1
        return j, docid - self.doc_bases[j]


class _CompositePostingsMap:
    def __init__(self, shard: _CompositeShard):
        self._shard = shard
        self._cache: Dict[str, Optional[_CompositePostings]] = {}

    def get(self, name: str):
        if name not in self._cache:
            pfs = [seg.postings.get(name)
                   for seg in self._shard.sub_segments]
            self._cache[name] = (
                _CompositePostings(pfs, self._shard.doc_bases,
                                   self._shard.n_docs)
                if any(pf is not None for pf in pfs) else None)
        return self._cache[name]


def _term_ranges(pf, term: str) -> List[Tuple[int, int]]:
    """Block ranges for a term — one per sub-segment on composites,
    a single contiguous range on plain PostingsFields."""
    ranges = getattr(pf, "term_block_ranges", None)
    if ranges is not None:
        return ranges(term)
    tid = pf.term_id(term)
    if tid < 0:
        return []
    return [(int(pf.term_block_start[tid]),
             int(pf.term_block_count[tid]))]


class MeshFieldState:
    """One field's postings stacked over shards, device-sharded."""

    def __init__(self, mesh: Mesh, pfs: List, n_docs_padded: int):
        s = len(pfs)
        tb_max = max((pf.block_docids.shape[0] for pf in pfs if pf is not None),
                     default=0)
        docids = np.zeros((s, tb_max + 1, BLOCK_SIZE), np.int32)
        tfs = np.zeros((s, tb_max + 1, BLOCK_SIZE), np.float32)
        lens = np.ones((s, n_docs_padded), np.float32)
        for i, pf in enumerate(pfs):
            if pf is None:
                continue
            tb = pf.block_docids.shape[0]
            docids[i, :tb] = pf.block_docids
            tfs[i, :tb] = pf.block_tfs
            nd = len(pf.field_lengths)
            lens[i, :nd] = np.maximum(pf.field_lengths, 1.0)
            lens[i, nd:] = max(float(pf.avg_field_length), 1.0)
        # leading axis is the shard axis; shard_map slices it per device
        shard_spec = NamedSharding(mesh, P("shard"))
        self.block_docids = jax.device_put(docids, shard_spec)
        self.block_tfs = jax.device_put(tfs, shard_spec)
        self.doc_lens = jax.device_put(lens, shard_spec)
        self.zero_block = tb_max      # common reserved all-zeros block row
        self.pfs = pfs                # host term dicts for binding


class MeshVectorState:
    """One dense-vector field stacked over shards, device-sharded —
    the ``P("shard")`` analogue of per-node DeviceVectors slabs
    (ops/device.py). Slab values are IDENTICAL to the per-shard device
    cache's (same prepare_vectors, same dtype), so mesh kNN scores are
    byte-identical to the per-shard loop's."""

    def __init__(self, mesh: Mesh, segments: List, field: str,
                 n_docs_padded: int, dtype):
        from elasticsearch_tpu.ops.vector import prepare_vectors
        vvs = [seg.vectors.get(field) if hasattr(seg, "vectors") else None
               for seg in segments]
        self.hosts = vvs              # host slabs for the exact re-rank
        sims = {vv.similarity for vv in vvs if vv is not None}
        self.similarity = next(iter(sims)) if len(sims) == 1 else None
        dims = next((vv.dims for vv in vvs if vv is not None), 1)
        s = len(segments)
        slab = np.zeros((s, n_docs_padded, dims), np.dtype(dtype))
        sqn = np.zeros((s, n_docs_padded), np.float32)
        hv = np.zeros((s, n_docs_padded), bool)
        for i, vv in enumerate(vvs):
            if vv is None or self.similarity is None:
                continue
            prepped, norms = prepare_vectors(vv.vectors, self.similarity,
                                             dtype)
            n = prepped.shape[0]
            slab[i, :n] = prepped
            sqn[i, :n] = (norms * norms).astype(np.float32)
            hv[i, :len(vv.has_value)] = vv.has_value
        shard_spec = NamedSharding(mesh, P("shard"))
        self.vectors = jax.device_put(slab, shard_spec)
        self.sq_norms = jax.device_put(sqn, shard_spec)
        self.has_value = jax.device_put(hv, shard_spec)
        self.dtype = self.vectors.dtype


class MeshCorpus:
    """A multi-shard index resident on a device mesh (one shard per
    device), built lazily per field from each shard's single segment."""

    def __init__(self, mesh: Mesh, segments: List[Segment]):
        self.mesh = mesh
        self.segments = segments
        self.n_shards = len(segments)
        nd = max((seg.n_docs for seg in segments), default=1)
        self.n_docs_padded = max(DOC_PAD, _round_up(nd, DOC_PAD))
        self.live_versions: Tuple[int, ...] = ()
        self.live = None
        self.refresh_live()
        self._fields: Dict[str, MeshFieldState] = {}
        self._vfields: Dict[Tuple[str, str], Optional[MeshVectorState]] = {}

    def refresh_live(self) -> None:
        """Deletes touch only the live bitmaps — re-upload just those
        (postings are immutable per segment, like the per-shard device
        cache's live-only refresh, search/context.py)."""
        versions = tuple(seg.live_version for seg in self.segments)
        if self.live is not None and versions == self.live_versions:
            return
        live = np.zeros((self.n_shards, self.n_docs_padded), bool)
        for i, seg in enumerate(self.segments):
            live[i, : seg.n_docs] = seg.live
        self.live = jax.device_put(
            live, NamedSharding(self.mesh, P("shard")))
        self.live_versions = versions

    def field(self, name: str) -> Optional[MeshFieldState]:
        if name not in self._fields:
            pfs = [seg.postings.get(name) for seg in self.segments]
            if all(pf is None for pf in pfs):
                return None
            self._fields[name] = MeshFieldState(
                self.mesh, pfs, self.n_docs_padded)
        return self._fields[name]

    def vector_field(self, name: str, dtype) -> Optional[MeshVectorState]:
        key = (name, str(np.dtype(dtype)))
        if key not in self._vfields:
            vs = MeshVectorState(self.mesh, self.segments, name,
                                 self.n_docs_padded, dtype)
            self._vfields[key] = vs if vs.similarity is not None else None
        return self._vfields[key]

    def device_arrays(self):
        """Every mesh-resident array of this corpus, tagged by slab
        class (the per-device HBM residency surface)."""
        if self.live is not None:
            yield "live_mask", self.live
        for fs in self._fields.values():
            yield "postings", fs.block_docids
            yield "postings", fs.block_tfs
            yield "norms", fs.doc_lens
        for vs in self._vfields.values():
            if vs is not None:
                yield "vectors", vs.vectors
                yield "vectors", vs.sq_norms
                yield "vectors", vs.has_value


def plans_mesh_compatible(plans: List[LogicalPlan]) -> bool:
    """All shards compiled the same query to the same structure with no
    dense factors (dense columns are not mesh-resident yet)."""
    if any(p is None for p in plans):
        return False
    p0 = plans[0]
    if any(p.dense for p in plans):
        return False
    for p in plans[1:]:
        if (len(p.groups) != len(p0.groups) or p.combine != p0.combine
                or p.msm != p0.msm or p.n_must != p0.n_must
                or p.n_filter != p0.n_filter):
            return False
    return True


def bind_mesh(corpus: MeshCorpus, plans: List[LogicalPlan]):
    """Bind one LogicalPlan per shard (weights/consts carry each shard's
    own idf) into stacked [S, ...] selection + group arrays. Returns None
    when a referenced field has no postings anywhere."""
    s = corpus.n_shards
    p0 = plans[0]
    ngroups = len(p0.groups)

    field_names: List[str] = []
    seen = set()
    for g in p0.groups:
        for t in g.terms:
            if t.field not in seen:
                seen.add(t.field)
                field_names.append(t.field)

    per_field_sel: Dict[str, List[Tuple[list, list, list, list, list]]] = {}
    for fname in field_names:
        fs = corpus.field(fname)
        if fs is None:
            continue
        shard_sels = []
        for si in range(s):
            pf = fs.pfs[si]
            ids: List[int] = []
            grps: List[int] = []
            subs: List[int] = []
            ws: List[float] = []
            consts: List[bool] = []
            if pf is not None:
                for gi, g in enumerate(plans[si].groups):
                    for t in g.terms:
                        if t.field != fname:
                            continue
                        for start, count in _term_ranges(pf, t.term):
                            ids.extend(range(start, start + count))
                            grps.extend([gi] * count)
                            subs.extend([t.sub] * count)
                            ws.extend([t.weight] * count)
                            consts.extend([t.const] * count)
            shard_sels.append((ids, grps, subs, ws, consts))
        per_field_sel[fname] = shard_sels

    if not per_field_sel:
        return None

    streams = []
    shard_spec = NamedSharding(corpus.mesh, P("shard"))
    for fname, shard_sels in per_field_sel.items():
        fs = corpus.field(fname)
        nb = block_bucket(max(1, max(len(e[0]) for e in shard_sels)))
        sel = np.full((s, nb), fs.zero_block, np.int32)
        grp = np.full((s, nb), ngroups, np.int32)
        sub = np.zeros((s, nb), np.int32)
        w = np.zeros((s, nb), np.float32)
        cst = np.zeros((s, nb), bool)
        avg = np.ones(s, np.float32)
        for si, (ids, grps, subs, ws, consts) in enumerate(shard_sels):
            n = len(ids)
            sel[si, :n] = ids
            grp[si, :n] = grps
            sub[si, :n] = subs
            w[si, :n] = ws
            cst[si, :n] = consts
            pf = fs.pfs[si]
            if pf is not None:
                avg[si] = max(float(pf.avg_field_length), 1.0)
        streams.append(plan_ops.FieldStream(
            fs.block_docids, fs.block_tfs, fs.doc_lens,
            jax.device_put(avg, shard_spec),
            jax.device_put(sel, shard_spec),
            jax.device_put(grp, shard_spec),
            jax.device_put(sub, shard_spec),
            jax.device_put(w, shard_spec),
            jax.device_put(cst, shard_spec)))

    gpad = max(4, block_bucket(max(1, ngroups)))
    kind = np.full((s, gpad), plan_ops.FILTER, np.int32)
    req = np.full((s, gpad), 1 << 30, np.int32)
    const = np.full((s, gpad), np.nan, np.float32)
    for si, p in enumerate(plans):
        for gi, g in enumerate(p.groups):
            kind[si, gi] = g.kind
            req[si, gi] = g.req
            const[si, gi] = g.const_score
    bonus = np.asarray([p.bonus for p in plans], np.float32)
    return (streams,
            jax.device_put(kind, shard_spec),
            jax.device_put(req, shard_spec),
            jax.device_put(const, shard_spec),
            jax.device_put(bonus, shard_spec))


# ---------------------------------------------------------------------------
# Mesh kNN kernels: the dense-vector analogue of plan_topk_mesh. Scoring
# mirrors KnnQuery.do_execute (search/queries.py) OPERATION FOR
# OPERATION — same formulas, same masking order, same cut semantics —
# so a mesh-served kNN `_search` is byte-identical to the per-shard
# dense loop it replaces.
# ---------------------------------------------------------------------------


def _knn_local_scores(vectors, sq_norms, has_value, qvec, similarity):
    """Per-shard (scores, mask) through the SAME ops/vector.py kernels
    and ES transforms KnnQuery.do_execute uses — shared code, not
    copies, so the mesh path cannot numerically drift from the
    per-shard loop."""
    from elasticsearch_tpu.ops import vector as vec_ops
    q = qvec[None, :]
    if similarity == "cosine":
        scores = (1.0 + vec_ops.cosine_scores(q, vectors)[0]) / 2.0
    elif similarity == "dot_product":
        scores = (1.0 + vec_ops.dot_scores(q, vectors)[0]) / 2.0
    else:  # l2_norm
        neg_sq = vec_ops.l2_scores(q, vectors, sq_norms)[0]
        scores = 1.0 / (1.0 - neg_sq)
    mask = has_value
    return jnp.where(mask, scores, 0.0), mask


@tracked_jit("mesh_knn_nominate",
             static_argnames=("mesh", "similarity", "nc"))
def _mesh_knn_nominate(vectors, sq_norms, has_value, qvec,
                       mesh: Mesh, similarity: str, nc: int):
    """Quantized-slab nomination: per-shard top-``nc`` candidate ids
    (the ids KnnQuery._exact_rerank reads back per shard — here ONE
    [S, nc] readback for the whole mesh)."""

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard"), P("shard"), P()),
             out_specs=P("shard"))
    def step(v, sn, hv, q):
        scores, _ = _knn_local_scores(v[0], sn[0], hv[0], q, similarity)
        _, ids = jax.lax.top_k(scores, nc)
        return ids[None, :]

    return step(vectors, sq_norms, has_value, qvec)


@tracked_jit("mesh_knn_step",
             static_argnames=("mesh", "nd", "similarity", "boost",
                              "cut", "k", "with_patch"))
def _mesh_knn_step(vectors, sq_norms, has_value, live, qvec,
                   patch_ids, patch_vals, mesh: Mesh, nd: int,
                   similarity: str, boost: float, cut: int, k: int,
                   with_patch: bool):
    """The full mesh kNN program: per-shard scoring (+ optional exact
    re-rank patch + candidate cut, mirroring KnnQuery.do_execute), live
    mask, psum'd totals, per-shard top-k and the all_gather merge —
    one packed readback. ``cut=0`` disables the per-shard candidate
    cut (cut >= n_docs_padded on the per-shard path)."""

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                       P(), P("shard"), P("shard")),
             out_specs=P())
    def step(v, sn, hv, lv, q, pid, pv):
        scores, mask = _knn_local_scores(v[0], sn[0], hv[0], q,
                                         similarity)
        if with_patch:
            # the exact-f32 re-rank scatter (KnnQuery._exact_rerank):
            # pad lanes carry unique out-of-range ids and drop
            scores = scores.at[pid[0]].set(pv[0], mode="drop",
                                           unique_indices=True)
        if cut:
            kth = jnp.sort(jnp.where(mask, scores, -jnp.inf))[nd - cut]
            mask = mask & (scores >= kth)
            scores = jnp.where(mask, scores, 0.0)
        if boost != 1.0:
            scores = scores * boost
        mask = mask & lv[0]
        vals, ids = jax.lax.top_k(jnp.where(mask, scores, -jnp.inf), k)
        shard_idx = jax.lax.axis_index("shard").astype(jnp.int32)
        gids = jnp.where(vals > -jnp.inf, ids + shard_idx * nd,
                         plan_ops._SENTINEL)
        av = jax.lax.all_gather(vals, "shard")
        ag = jax.lax.all_gather(gids, "shard")
        tv, ti = jax.lax.top_k(av.reshape(-1), k)
        tg = jnp.take(ag.reshape(-1), ti)
        tg = jnp.where(tv > -jnp.inf, tg, plan_ops._SENTINEL)
        total = jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), "shard")
        return plan_ops.pack_result(tv, tg, total)

    return step(vectors, sq_norms, has_value, live, qvec,
                patch_ids, patch_vals)


class MeshSearchBackend:
    """Service-side entry: caches MeshCorpus per shard-set epoch and runs
    compatible multi-shard queries as one SPMD launch.

    Dispatches count under ``dispatch.<axis>`` (``shard`` = sharded-
    corpus SPMD serving, ``replica`` = query-cohort fan-out via the
    replica helpers); every refusal counts under ``fallback.<reason>``
    and the caller runs the per-shard loop — fallback is ALWAYS clean
    (no error surfaces to the request). ``metrics`` (a node
    MetricsRegistry, wired by Node) mirrors both as
    ``search.mesh.dispatch{axis}`` / ``search.mesh.fallback{reason}``.
    """

    #: replica-corpus handle cache bound (strong refs pin sources, which
    #: are long-lived registration/device-cache arrays anyway)
    REPLICA_CACHE_MAX = 64

    def __init__(self, max_cached: int = 4, min_devices: int = 2):
        from collections import OrderedDict
        self._cache: Dict[tuple, MeshCorpus] = {}
        self._cache_lock = threading.Lock()
        self._max_cached = max_cached
        self.min_devices = min_devices
        self.mesh_searches = 0   # stat: queries served via the mesh
        self.counters: Dict[str, int] = {}
        self.metrics = None      # node MetricsRegistry (wired by Node)
        self._replica_meshes: Dict[int, Mesh] = {}
        # LRU (touch-on-hit): churning entries (the fastpath mask stack
        # swaps identity on every filter-row update) age out while the
        # hot corpus handles stay resident
        self._replicated: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._replica_lock = threading.Lock()

    # ------------------------------------------------------------- gates
    @staticmethod
    def enabled() -> bool:
        """Kill switch: ``ESTPU_MESH_SERVING=0`` forces the per-shard
        loop everywhere (fallback counters still tick)."""
        return os.environ.get("ESTPU_MESH_SERVING", "1") != "0"

    @staticmethod
    def available_devices() -> int:
        return len(jax.devices())

    def _count(self, key: str, n: int = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + n

    def _dispatch(self, axis: str, n: int = 1) -> None:
        self._count(f"dispatch.{axis}", n)
        if self.metrics is not None:
            self.metrics.inc("search.mesh.dispatch", n, axis=axis)

    def _fallback(self, reason: str) -> None:
        self._count(f"fallback.{reason}")
        if self.metrics is not None:
            self.metrics.inc("search.mesh.fallback", reason=reason)

    # ------------------------------------------------------------ corpus
    def corpus_for(self, index_name: str,
                   shard_segments: List[Segment]) -> MeshCorpus:
        # keyed by segment NAMES (postings identity); deletes only bump
        # live_version and refresh the live bitmaps in place
        key = (index_name, tuple(seg.name for seg in shard_segments))
        with self._cache_lock:
            corpus = self._cache.get(key)
            if corpus is None:
                from elasticsearch_tpu.parallel.sharded import make_mesh
                mesh = make_mesh(n_shards=len(shard_segments))
                corpus = MeshCorpus(mesh, shard_segments)
                while len(self._cache) >= self._max_cached:
                    self._cache.pop(next(iter(self._cache)))
                self._cache[key] = corpus
            else:
                corpus.segments = shard_segments
                corpus.refresh_live()
        return corpus

    # ------------------------------------------------------------- stats
    def residency(self) -> Dict[str, Dict[str, int]]:
        """Per-DEVICE resident bytes by slab class over every cached
        mesh corpus — the `GET /_kernels` mesh.residency surface (the
        one-Lucene-shard-per-data-node HBM analogue, per chip)."""
        out: Dict[str, Dict[str, int]] = {}
        with self._cache_lock:
            corpora = list(self._cache.values())
        for corpus in corpora:
            for klass, arr in corpus.device_arrays():
                try:
                    shards = arr.addressable_shards
                except Exception:
                    continue
                for sh in shards:
                    dev = out.setdefault(str(sh.device), {})
                    dev[klass] = dev.get(klass, 0) + int(sh.data.nbytes)
        return out

    def stats(self) -> Dict[str, object]:
        with self._replica_lock:
            rep_bytes = sum(e[1].nbytes for e in self._replicated.values())
        return {
            "enabled": self.enabled(),
            "devices": self.available_devices(),
            "mesh_searches": self.mesh_searches,
            "counters": dict(sorted(self.counters.items())),
            "residency": self.residency(),
            "replica_corpus_bytes": int(rep_bytes),
        }

    # ---------------------------------------------- replica-axis helpers
    #
    # The second serving mode the tentpole names: query COHORTS (the
    # continuous-batching launches of search/batching.py and the native
    # front's fastpath cohorts) fan across a 1-D ("replica",) mesh —
    # corpus replicated (P()), the cohort's per-query rows sharded
    # P("replica"). The SAME jitted kernels run; GSPMD partitions the
    # vmapped program over the query axis (the pjit/PartitionSpec
    # pattern, SNIPPETS.md [2][3]), so per-query results stay
    # byte-identical to the single-device launch.

    def replica_mesh_for(self, q_rows: int) -> Optional[Mesh]:
        """Largest power-of-two ("replica",) mesh that divides a
        ``q_rows``-row cohort, or None when fewer than min_devices
        devices exist (the caller launches single-device)."""
        if not self.enabled():
            return None
        try:
            devices = jax.devices()
        except Exception:
            return None
        n = 1
        while n * 2 <= min(q_rows, len(devices)):
            n *= 2
        if n < max(2, self.min_devices):
            return None
        mesh = self._replica_meshes.get(n)
        if mesh is None:
            mesh = Mesh(np.asarray(devices[:n]), ("replica",))
            self._replica_meshes[n] = mesh
        return mesh

    def replicated(self, mesh: Mesh, arr):
        """A fully-replicated (P()) handle of a device/host corpus
        array, cached by source identity (sources are long-lived corpus
        arrays; a refresh swaps the source object and naturally
        re-replicates)."""
        key = (id(mesh), id(arr))
        with self._replica_lock:
            entry = self._replicated.get(key)
            if entry is not None and entry[0] is arr:
                self._replicated.move_to_end(key)
                return entry[1]
        rep = jax.device_put(arr, NamedSharding(mesh, P()))
        with self._replica_lock:
            self._replicated[key] = (arr, rep)
            while len(self._replicated) > self.REPLICA_CACHE_MAX:
                self._replicated.popitem(last=False)
        return rep

    def shard_rows(self, mesh: Mesh, arr):
        """Shard a cohort's leading (query) axis over the replica mesh."""
        return jax.device_put(arr, NamedSharding(mesh, P("replica")))

    # ----------------------------------------------------------- serving
    def execute(self, index_name: str, searchers, query,
                k: int) -> Optional[Tuple[list, int]]:
        """Try the mesh path: searchers = the index's per-shard
        ShardSearchers. Returns ([(shard_idx, seg_idx, local_docid,
        score)], total) sorted by (-score, shard, docid), or None to
        fall back to the per-shard loop (typed fallback counter)."""
        if not self.enabled():
            self._fallback("disabled")
            return None
        n_shards = len(searchers)
        if k < 1:
            self._fallback("size_zero")
            return None   # size:0 — per-shard path keeps max_score semantics
        if n_shards < 2:
            self._fallback("single_shard")
            return None
        if self.available_devices() < n_shards:
            self._fallback("not_enough_devices")
            return None
        if any(len(s.segments) == 0 for s in searchers):
            self._fallback("empty_shard")
            return None
        if any(getattr(s, "dfs_global_stats", False) for s in searchers):
            # dfs_query_then_fetch scores every shard with AGGREGATED
            # statistics; the mesh residency binds each shard's own
            # stats (ES-default per-shard IDF) — the loop keeps dfs
            # exact (sharded_dfs_stats is the future on-mesh answer)
            self._fallback("dfs_stats")
            return None
        from elasticsearch_tpu.search.queries import KnnQuery
        if isinstance(query, KnnQuery):
            return self._execute_knn(index_name, searchers, query, k)
        if any(len(s.segments) != 1 for s in searchers) \
                and os.environ.get("ESTPU_MESH_COMPOSITE") != "1":
            # composite (multi-segment) residency concatenates a
            # shard's segments into ONE kernel array — the segmented
            # sums then round with a different cumsum prefix base than
            # the per-segment loop, so scores drift in the last float32
            # bits. The serving contract here is BYTE-identical results
            # (the scroll one-executor rule, searcher.py), so unmerged
            # shards take the per-shard loop; force-merged layouts (the
            # mesh residency model) serve on-mesh. ESTPU_MESH_COMPOSITE=1
            # opts into the approximate composite mode. Checked BEFORE
            # the per-shard compiles: an unmerged index must not pay
            # S plan compiles per request just to fall back.
            self._fallback("multi_segment")
            return None
        # probe shard 0 first: ineligible queries (dense factors, scripts,
        # sorts…) bail after ONE compile instead of S
        first = compile_plan(query.rewrite(searchers[0]), searchers[0])
        if first is None or first.dense:
            self._fallback("plan_incompatible")
            return None
        plans = [first]
        for s in searchers[1:]:
            rq = query.rewrite(s)
            plans.append(compile_plan(rq, s))
        if not plans_mesh_compatible(plans):
            self._fallback("plan_incompatible")
            return None
        shard_views = [s.segments[0] if len(s.segments) == 1
                       else _CompositeShard(list(s.segments))
                       for s in searchers]
        # float-pack id overflow guard: the packed readback carries
        # GLOBAL ids (shard * nd_padded + docid) as float32 casts, exact
        # only < 2^24 — past that, fall back to the per-shard RPC merge
        # instead of silently corrupting low docid bits
        nd_max = max((v.n_docs for v in shard_views), default=1)
        nd_padded = max(DOC_PAD, _round_up(nd_max, DOC_PAD))
        if n_shards * nd_padded >= plan_ops.PACKED_ID_LIMIT:
            import logging
            logging.getLogger(__name__).warning(
                "mesh fast path skipped: %d shards x %d padded docs "
                ">= 2^24 float-packed global-id ceiling; using the "
                "per-shard fallback", n_shards, nd_padded)
            self._fallback("packed_id_ceiling")
            return None
        corpus = self.corpus_for(index_name, shard_views)
        bound = bind_mesh(corpus, plans)
        if bound is None:
            self.mesh_searches += 1
            self._dispatch("shard")
            return [], 0   # no query term exists in any shard
        streams, gk, gr, gc, bo = bound
        p0 = plans[0]
        packed = self._launch(
            corpus, "plan_topk_mesh",
            lambda: plan_ops.plan_topk_mesh(
                streams, gk, gr, gc, bo, corpus.live, corpus.mesh,
                corpus.n_docs_padded, p0.n_must, p0.n_filter, p0.msm,
                float(p0.tie), float(searchers[0].k1),
                float(searchers[0].b), int(k), p0.combine))
        self.mesh_searches += 1
        self._dispatch("shard")
        return self._unpack_docs(corpus, packed, int(k))

    def _launch(self, corpus: MeshCorpus, kernel: str, fn):
        """Run one mesh launch under the profile seam: stage-timed as
        ``launch`` and, when a `profile: true` recorder is active,
        attributed per chip via a device record carrying the mesh shape
        and device list (the PR-8 record_device contract)."""
        from elasticsearch_tpu.search import profile as _prof
        recording = _prof.recording()
        t0 = _prof.now_ns() if recording else 0
        with _prof.span("launch"):
            out = fn()
            packed = np.asarray(out)   # ONE readback for the mesh query
        launch_ms = round((_prof.now_ns() - t0) / 1e6, 3) if recording \
            else 0.0
        if recording:
            _prof.record_device({
                "kernel": kernel,
                "mesh_shape": {"shard": corpus.n_shards},
                "device": [str(d) for d in
                           np.asarray(corpus.mesh.devices).flat],
                "launch_ms": launch_ms,
                "readback_bytes": int(packed.nbytes),
            })
        return packed

    def _unpack_docs(self, corpus: MeshCorpus, packed: np.ndarray,
                     k: int) -> Tuple[list, int]:
        vals, gids, total = plan_ops.unpack_result(packed, k)
        nd = corpus.n_docs_padded
        docs = []
        for v, g in zip(vals, gids):
            if v <= -np.inf:
                continue
            shard, docid = int(g) // nd, int(g) % nd
            view = corpus.segments[shard]
            if isinstance(view, _CompositeShard):
                seg_idx, docid = view.locate(docid)
            else:
                seg_idx = 0
            docs.append((shard, seg_idx, docid, float(v)))
        return docs, int(total)

    # --------------------------------------------------------------- kNN
    def _execute_knn(self, index_name: str, searchers, query,
                     k: int) -> Optional[Tuple[list, int]]:
        """Mesh path for a bare top-level kNN query: per-shard brute
        force + all_gather merge, byte-identical to the per-shard dense
        loop (KnnQuery per shard + coordinator merge). Quantized slabs
        keep the exact-f32 re-rank: one [S, nc] nomination readback,
        the same host numpy re-rank per shard, and the exact scores
        ride back into the final SPMD launch as a scatter patch."""
        if query.filter_query is not None:
            self._fallback("knn_filter")
            return None
        if any(len(s.segments) != 1 for s in searchers):
            self._fallback("knn_multi_segment")
            return None
        from elasticsearch_tpu.search.searcher import MAX_TOPK
        k = min(max(int(k), 1), MAX_TOPK)
        pads = {max(DOC_PAD, _round_up(s.segments[0].n_docs, DOC_PAD))
                for s in searchers}
        if len(pads) != 1:
            # the per-shard candidate cut / nomination depth clamp to
            # EACH shard's padded size — non-uniform pads would change
            # semantics shard by shard
            self._fallback("knn_nonuniform_padding")
            return None
        n_shards = len(searchers)
        nd = pads.pop()
        if n_shards * nd >= plan_ops.PACKED_ID_LIMIT:
            self._fallback("packed_id_ceiling")
            return None
        dtype = getattr(searchers[0].cache, "_vector_dtype", jnp.bfloat16)
        corpus = self.corpus_for(
            index_name, [s.segments[0] for s in searchers])
        vs = corpus.vector_field(query.field, dtype)
        if vs is None:
            self._fallback("knn_missing_field")
            return None
        if vs.similarity not in ("cosine", "dot_product", "l2_norm"):
            self._fallback("knn_similarity")
            return None
        qvec = jnp.asarray(np.asarray(query.query_vector, np.float32))
        cut = query.k or query.num_candidates
        cut = int(cut) if cut is not None and int(cut) < nd else 0
        quantized = vs.dtype != jnp.float32
        patch_ids = np.zeros((n_shards, 1), np.int32) + nd
        patch_vals = np.zeros((n_shards, 1), np.float32)
        if quantized:
            nc = int(query.num_candidates or 3 * (query.k or 1000))
            nc = min(nc, nd)
            ids = readback(
                "parallel.mesh_executor.knn_nominate",
                _mesh_knn_nominate(
                    vs.vectors, vs.sq_norms, vs.has_value, qvec,
                    corpus.mesh, vs.similarity, nc))   # [S, nc]
            patch_ids = np.zeros((n_shards, nc), np.int32)
            patch_vals = np.zeros((n_shards, nc), np.float32)
            for si in range(n_shards):
                vv = vs.hosts[si]
                # pad lanes: unique out-of-range targets (mode="drop")
                patch_ids[si] = nd + np.arange(nc, dtype=np.int32)
                if vv is None:
                    continue
                ids_h = ids[si][ids[si] < vv.vectors.shape[0]]
                from elasticsearch_tpu.ops.vector import (
                    exact_rerank_scores,
                )
                exact = exact_rerank_scores(
                    vv.vectors[ids_h],
                    np.asarray(query.query_vector, np.float32),
                    vs.similarity)
                patch_ids[si, :len(ids_h)] = ids_h
                patch_vals[si, :len(ids_h)] = exact
        shard_spec = NamedSharding(corpus.mesh, P("shard"))
        packed = self._launch(
            corpus, "mesh_knn_step",
            lambda: _mesh_knn_step(
                vs.vectors, vs.sq_norms, vs.has_value, corpus.live,
                qvec, jax.device_put(patch_ids, shard_spec),
                jax.device_put(patch_vals, shard_spec), corpus.mesh,
                nd, vs.similarity, float(query.boost), cut, k,
                quantized))
        self.mesh_searches += 1
        self._dispatch("knn")
        return self._unpack_docs(corpus, packed, k)


# Backwards-compatible name (pre-backend sessions): the executor IS the
# backend now.
MeshSearchExecutor = MeshSearchBackend
