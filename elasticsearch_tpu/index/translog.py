"""Translog: the per-shard write-ahead log.

Mirrors the reference's translog (ref: index/translog/Translog.java:87-98,
281-288,362): an append-only sequential op log in generation files with an
fsync'd checkpoint file; ops are replayed on recovery up to the last commit.
Generations roll on flush; `trim` drops generations below the last committed
one (retention beyond that is the soft-delete history's job).

Format: one op per line — length-prefixed JSON with a CRC32 trailer, so a
torn tail write is detected and truncated rather than corrupting recovery
(ref: Translog checksummed ops + TranslogCorruptedException).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from elasticsearch_tpu.common.errors import TranslogCorruptedException

_HEADER = struct.Struct("<I")   # payload length
_TRAILER = struct.Struct("<I")  # crc32


@dataclass
class TranslogOp:
    op_type: str            # "index" | "delete" | "noop"
    seq_no: int
    primary_term: int
    doc_id: Optional[str] = None
    source: Optional[Dict[str, Any]] = None
    version: int = 1

    def to_dict(self) -> Dict[str, Any]:
        d = {"op": self.op_type, "seq_no": self.seq_no,
             "primary_term": self.primary_term, "version": self.version}
        if self.doc_id is not None:
            d["id"] = self.doc_id
        if self.source is not None:
            d["source"] = self.source
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TranslogOp":
        return cls(op_type=d["op"], seq_no=d["seq_no"],
                   primary_term=d["primary_term"], doc_id=d.get("id"),
                   source=d.get("source"), version=d.get("version", 1))


@dataclass
class Checkpoint:
    """ref: index/translog/Checkpoint.java — the fsync'd pointer that makes
    the log crash-consistent."""

    generation: int
    num_ops: int
    min_seq_no: int
    max_seq_no: int

    def write(self, path: str):
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.__dict__, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)  # atomic on POSIX

    @classmethod
    def read(cls, path: str) -> "Checkpoint":
        with open(path) as fh:
            return cls(**json.load(fh))


class Translog:
    """Write path: add() appends to the current generation; sync() fsyncs
    and advances the checkpoint. rollGeneration() on flush."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        ckp_path = os.path.join(directory, "translog.ckp")
        if os.path.exists(ckp_path):
            ckp = Checkpoint.read(ckp_path)
            self.generation = ckp.generation
        else:
            self.generation = 1
            Checkpoint(1, 0, -1, -1).write(ckp_path)
        self._num_ops = 0
        self._min_seq = -1
        self._max_seq = -1
        self._fh = open(self._gen_path(self.generation), "ab")
        # restore counters from existing ops in the current generation
        for op in self._read_gen(self.generation):
            self._account(op)

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"translog-{gen}.log")

    def _ckp_path(self) -> str:
        return os.path.join(self.dir, "translog.ckp")

    def _account(self, op: TranslogOp):
        self._num_ops += 1
        if self._min_seq < 0 or op.seq_no < self._min_seq:
            self._min_seq = op.seq_no
        self._max_seq = max(self._max_seq, op.seq_no)

    def add(self, op: TranslogOp) -> None:
        payload = json.dumps(op.to_dict(), separators=(",", ":")).encode()
        crc = zlib.crc32(payload)
        with self._lock:
            self._fh.write(_HEADER.pack(len(payload)))
            self._fh.write(payload)
            self._fh.write(_TRAILER.pack(crc))
            self._account(op)

    def sync(self) -> None:
        """fsync data then checkpoint (ref: request-durability policy)."""
        with self._lock:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            Checkpoint(self.generation, self._num_ops,
                       self._min_seq, self._max_seq).write(self._ckp_path())

    def roll_generation(self) -> int:
        with self._lock:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self.generation += 1
            self._num_ops = 0
            self._min_seq = -1
            self._max_seq = -1
            self._fh = open(self._gen_path(self.generation), "ab")
            Checkpoint(self.generation, 0, -1, -1).write(self._ckp_path())
            return self.generation

    def trim_generations(self, keep_from: int) -> None:
        """Delete generations below keep_from (called after commit)."""
        with self._lock:
            for gen in range(1, keep_from):
                p = self._gen_path(gen)
                if os.path.exists(p):
                    os.remove(p)

    def _read_gen(self, gen: int) -> Iterator[TranslogOp]:
        path = self._gen_path(gen)
        if not os.path.exists(path):
            return
        with open(path, "rb") as fh:
            data = fh.read()
        pos = 0
        while pos < len(data):
            if pos + _HEADER.size > len(data):
                break  # torn header → truncate
            (length,) = _HEADER.unpack_from(data, pos)
            end = pos + _HEADER.size + length + _TRAILER.size
            if end > len(data):
                break  # torn payload → truncate
            payload = data[pos + _HEADER.size : pos + _HEADER.size + length]
            (crc,) = _TRAILER.unpack_from(data, pos + _HEADER.size + length)
            if zlib.crc32(payload) != crc:
                raise TranslogCorruptedException(
                    f"translog corruption in generation {gen} at offset {pos}")
            yield TranslogOp.from_dict(json.loads(payload))
            pos = end

    def read_ops(self, from_generation: int = 1) -> List[TranslogOp]:
        """All ops from from_generation to current (recovery replay,
        ref: InternalEngine.recoverFromTranslog)."""
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()  # make buffered appends visible to readers
        ops: List[TranslogOp] = []
        for gen in range(from_generation, self.generation + 1):
            ops.extend(self._read_gen(gen))
        return ops

    def stats(self) -> Dict[str, Any]:
        return {"operations": self._num_ops, "generation": self.generation}

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()
                self._fh.close()
