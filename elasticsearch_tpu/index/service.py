"""Index and indices services: the per-index shard group + node registry.

Mirrors the reference's IndexService/IndicesService (ref: index/
IndexService.java, indices/IndicesService.java; routing ref:
cluster/routing/OperationRouting.java:42 — docs route to shards by
murmur3(routing) % num_shards). An index here is N local shard engines
(the data-parallel partitioning axis that maps onto device meshes in
``parallel/``); searches fan out over shards and merge, writes route by id.
"""

from __future__ import annotations

import json
import os
import shutil
import struct
import time
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IndexNotFoundException,
    IllegalArgumentException,
    ResourceAlreadyExistsException,
)
from elasticsearch_tpu.common.settings import (
    INDEX_BM25_B,
    INDEX_BM25_K1,
    INDEX_NUMBER_OF_SHARDS,
    Settings,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.search.searcher import ShardSearcher


from elasticsearch_tpu import native as _native

# resolved once: the routing hash runs per document on the bulk path
_NATIVE_M3 = None
if _native.get_lib() is not None:
    _NATIVE_M3 = _native.get_lib().murmur3_hash_utf16le


def murmur3_hash(key: str) -> int:
    """32-bit murmur3 (x86, seed 0) over the UTF-16LE bytes of the routing
    key — bit-exact with the reference's Murmur3HashFunction (ref:
    cluster/routing/Murmur3HashFunction.java hashes char low/high bytes)
    so doc→shard assignment agrees. Native fast path when the host
    runtime is available (routing runs per document on the bulk path)."""
    if _NATIVE_M3 is not None:
        data = key.encode("utf-16-le")
        return int(_NATIVE_M3(data, len(data)))
    data = key.encode("utf-16-le")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = 0
    rounded = len(data) & ~0x3
    for i in range(0, rounded, 4):
        (k,) = struct.unpack_from("<i", data, i)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = len(data) & 0x3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    # to signed 32-bit, matching Java
    return h - 0x100000000 if h >= 0x80000000 else h


class IndexService:
    """One index: settings + mappings + N shard engines."""

    def __init__(self, name: str, path: str, settings: Settings,
                 mappings: Optional[Dict[str, Any]] = None,
                 device_cache: Optional[DeviceSegmentCache] = None):
        self.name = name
        self.path = path
        if settings.get("index.creation_date") is None:
            flat = settings.as_dict()
            flat["index.creation_date"] = int(time.time() * 1000)
            settings = Settings(flat)
        self.settings = settings
        self.num_shards = INDEX_NUMBER_OF_SHARDS.get(settings)
        self.k1 = INDEX_BM25_K1.get(settings)
        self.b = INDEX_BM25_B.get(settings)
        self.mapper = MapperService(settings, mappings)
        self.device_cache = device_cache or DeviceSegmentCache()
        os.makedirs(path, exist_ok=True)
        self.shards: List[Engine] = [
            Engine(os.path.join(path, str(shard_id)), self.mapper)
            for shard_id in range(self.num_shards)
        ]
        self._known_seg_names: set = {
            seg.name for shard in self.shards for seg in shard.segments}
        self.indexing_slowlog_recent: List[Dict[str, Any]] = []
        self._index_slowlog_thresholds = self._parse_slowlog_thresholds()
        self._persist_meta()

    # ---------------------------------------------------------- metadata
    def _persist_meta(self):
        meta = {"settings": self.settings.as_dict(),
                "mappings": self.mapper.to_mapping()}
        tmp = os.path.join(self.path, "_meta.json.tmp")
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.replace(tmp, os.path.join(self.path, "_meta.json"))

    def update_mappings(self, mappings: Dict[str, Any]):
        self.mapper.merge(mappings)
        self._persist_meta()

    def update_settings(self, updates: Dict[str, Any]):
        """Merge dynamic setting updates (ref: the update-settings action;
        static settings like number_of_shards are rejected)."""
        flat = Settings.from_dict(updates).as_dict()
        for k in flat:
            if k in ("index.number_of_shards",):
                from elasticsearch_tpu.common.errors import (
                    IllegalArgumentException)
                raise IllegalArgumentException(
                    f"final {self.name} setting [{k}], not updateable")
        merged = self.settings.as_dict()
        merged.update(flat)
        self.settings = Settings(merged)
        self._index_slowlog_thresholds = self._parse_slowlog_thresholds()
        self._persist_meta()

    # ------------------------------------------------------- state blocks
    @property
    def is_closed(self) -> bool:
        """Closed indices hold their data but serve no reads/writes
        (ref: MetadataIndexStateService close/open)."""
        return str(self.settings.get("index.state", "open")) == "close"

    @property
    def is_frozen(self) -> bool:
        """Frozen indices are searchable but keep no device-resident
        state between searches (ref: x-pack frozen-indices FrozenEngine's
        per-search reader — here: per-search HBM residency)."""
        return str(self.settings.get("index.frozen",
                                     "false")).lower() == "true"

    @property
    def write_blocked(self) -> bool:
        for key in ("index.blocks.write", "index.blocks.read_only"):
            if str(self.settings.get(key, "false")).lower() == "true":
                return True
        return self.is_closed

    def check_write_block(self):
        if self.write_blocked:
            from elasticsearch_tpu.common.errors import (
                ClusterBlockException)
            reason = ("closed" if self.is_closed else "read-only")
            raise ClusterBlockException(
                f"index [{self.name}] blocked: {reason}")

    # ------------------------------------------------------------ routing
    def shard_for(self, doc_id: str, routing: Optional[str] = None) -> int:
        key = routing if routing is not None else doc_id
        return abs(murmur3_hash(key)) % self.num_shards

    # ------------------------------------------------------------- writes
    def index_doc(self, doc_id: str, source: Dict[str, Any],
                  routing: Optional[str] = None, **kwargs):
        self.check_write_block()
        if routing is None:
            # child docs route by parent id so they land on the parent's
            # shard (see DocumentMapper.join_parent_routing)
            routing = self.mapper.mapper.join_parent_routing(source)
        shard = self.shards[self.shard_for(doc_id, routing)]
        n_fields = len(self.mapper.mapper.fields)
        t0 = time.monotonic()
        result = shard.index(doc_id, source, **kwargs)
        self._maybe_indexing_slowlog(doc_id, time.monotonic() - t0)
        if len(self.mapper.mapper.fields) != n_fields:
            # dynamic mappings grew during parse; keep _meta fresh
            self._persist_meta()
        return result

    def _parse_slowlog_thresholds(self):
        """Thresholds parse ONCE per settings change, not per document
        (ref: IndexingSlowLog re-reads settings only on update)."""
        from elasticsearch_tpu.common.settings import parse_time_value
        out = []
        for level, py_level in (("warn", 30), ("info", 20),
                                ("debug", 10), ("trace", 5)):
            thr = self.settings.get(
                f"index.indexing.slowlog.threshold.index.{level}")
            if thr is None:
                continue
            thr_s = parse_time_value(str(thr), "slowlog")
            if thr_s < 0:
                continue                      # -1 disables the level
            out.append((level, py_level, thr_s))
        return out

    def _maybe_indexing_slowlog(self, doc_id: str, took_s: float):
        """Per-index indexing slow log (ref: index/IndexingSlowLog.java)."""
        for level, py_level, thr_s in self._index_slowlog_thresholds:
            if took_s >= thr_s:
                import logging
                logging.getLogger("index.indexing.slowlog").log(
                    py_level, "[%s] took[%.1fms], id[%s]",
                    self.name, took_s * 1000, doc_id)
                self.indexing_slowlog_recent.append(
                    {"index": self.name, "id": doc_id, "level": level,
                     "took_ms": took_s * 1000})
                while len(self.indexing_slowlog_recent) > 128:
                    self.indexing_slowlog_recent.pop(0)
                break

    def delete_doc(self, doc_id: str, routing: Optional[str] = None, **kwargs):
        self.check_write_block()
        return self.shards[self.shard_for(doc_id, routing)].delete(doc_id, **kwargs)

    def get_doc(self, doc_id: str, routing: Optional[str] = None):
        if self.is_closed:
            from elasticsearch_tpu.common.errors import (
                IndexClosedException)
            raise IndexClosedException(self.name)
        return self.shards[self.shard_for(doc_id, routing)].get(doc_id)

    def refresh(self):
        for shard in self.shards:
            shard.refresh()
        self._gc_device_cache()

    def flush(self):
        for shard in self.shards:
            shard.flush()
        self._gc_device_cache()

    def force_merge(self, max_num_segments: int = 1):
        for shard in self.shards:
            shard.force_merge(max_num_segments)
        self._gc_device_cache()

    def _gc_device_cache(self):
        """Evict device copies of segments retired by merges (segment names
        are globally unique, so eviction can't hit another index)."""
        current = {seg.name for shard in self.shards for seg in shard.segments}
        stale = self._known_seg_names - current
        if stale:
            self.device_cache.evict(stale)
        self._known_seg_names = current

    # ------------------------------------------------------------ search
    def shard_searchers(self) -> List[ShardSearcher]:
        out = []
        for shard in self.shards:
            snap = shard.acquire_searcher()
            s = ShardSearcher(snap.segments, self.mapper,
                              self.device_cache, self.k1, self.b)
            # the snapshot epoch travels with the searcher so request-
            # cache keys stay atomically consistent with the data read
            s.epoch = snap.epoch
            out.append(s)
        return out

    def stats(self) -> Dict[str, Any]:
        docs = 0
        deleted = 0
        segments = 0
        # engine-level device stats for THIS index's resident segments
        # (the device cache is node-shared; segment names are globally
        # unique, so the slice is exact) — the TPU-HBM analogue of the
        # reference's per-index segment/fielddata memory in `_stats`.
        # ONE walk per shard; shards partition the segment set, so the
        # index view is the sum of the per-shard views.
        shard_hbm: List[int] = []
        by_class: Dict[str, int] = {}
        resident = 0
        seg_names = set()
        for shard in self.shards:
            s = shard.stats()
            docs += s["docs"]["count"]
            deleted += s["docs"]["deleted"]
            segments += s["segments"]["count"]
            shard_names = {seg.name for seg in shard.segments}
            seg_names |= shard_names
            sh = self.device_cache.hbm_stats(shard_names)
            shard_hbm.append(sh["total_bytes"])
            resident += sh["segments"]
            for cls, n in sh["by_class"].items():
                by_class[cls] = by_class.get(cls, 0) + n
        total = sum(shard_hbm)
        self._hbm_peak = max(getattr(self, "_hbm_peak", 0), total)
        hbm = {"total_bytes": total, "by_class": by_class,
               "segments": resident, "peak_bytes": self._hbm_peak,
               "shard_bytes": shard_hbm}
        return {"docs": {"count": docs, "deleted": deleted},
                "segments": {"count": segments},
                "shards": self.num_shards,
                "engine": {
                    "hbm": hbm,
                    "caches": self.device_cache.cache_stats(seg_names)}}

    def close(self):
        for shard in self.shards:
            shard.close()


class IndicesService:
    """Node-level index registry with disk persistence + reopen."""

    def __init__(self, data_path: str, node_settings: Settings = Settings.EMPTY):
        self.data_path = data_path
        self.node_settings = node_settings
        self.indices: Dict[str, IndexService] = {}
        self.device_cache = DeviceSegmentCache()
        # alias/data-stream resolution hooks (set by MetadataService):
        # name -> list of concrete indices, or None if not an abstraction
        self.name_resolver = None
        # () -> {abstraction name: [indices]} for wildcard expansion
        self.abstraction_lister = None
        # callbacks fired when an index is deleted (metadata cleanup)
        self.delete_listeners = []
        os.makedirs(data_path, exist_ok=True)
        for name in sorted(os.listdir(data_path)):
            meta_path = os.path.join(data_path, name, "_meta.json")
            if os.path.exists(meta_path):
                with open(meta_path) as fh:
                    meta = json.load(fh)
                self.indices[name] = IndexService(
                    name, os.path.join(data_path, name),
                    Settings(meta["settings"]), meta["mappings"],
                    self.device_cache)

    @staticmethod
    def validate_index_name(name: str) -> None:
        if not name or name.startswith(("_", "-")) or name != name.lower():
            raise IllegalArgumentException(
                f"Invalid index name [{name}], must be lowercase and not "
                f"start with '_' or '-'")

    def create_index(self, name: str, settings: Optional[Dict[str, Any]] = None,
                     mappings: Optional[Dict[str, Any]] = None) -> IndexService:
        if name in self.indices:
            raise ResourceAlreadyExistsException(f"index [{name}]")
        self.validate_index_name(name)
        idx = IndexService(name, os.path.join(self.data_path, name),
                           Settings.from_dict(settings or {}), mappings,
                           self.device_cache)
        self.indices[name] = idx
        return idx

    def open_index(self, name: str) -> IndexService:
        """Open an index whose files were placed under the data path out of
        band (snapshot restore, peer-recovery file copy)."""
        if name in self.indices:
            raise ResourceAlreadyExistsException(f"index [{name}]")
        meta_path = os.path.join(self.data_path, name, "_meta.json")
        if not os.path.exists(meta_path):
            raise IndexNotFoundException(name)
        with open(meta_path) as fh:
            meta = json.load(fh)
        idx = IndexService(name, os.path.join(self.data_path, name),
                           Settings(meta["settings"]), meta["mappings"],
                           self.device_cache)
        self.indices[name] = idx
        return idx

    def get(self, name: str) -> IndexService:
        idx = self.indices.get(name)
        if idx is None:
            raise IndexNotFoundException(name)
        return idx

    def has(self, name: str) -> bool:
        return name in self.indices

    def delete_index(self, name: str):
        idx = self.get(name)
        idx.close()
        self.device_cache.evict(idx._known_seg_names)
        del self.indices[name]
        shutil.rmtree(idx.path, ignore_errors=True)
        for listener in self.delete_listeners:
            listener(name)

    def resolve(self, expression: str,
                allow_closed: bool = False) -> List[str]:
        """Index name expression: csv, wildcards, _all (ref:
        IndexNameExpressionResolver). Wildcards expand over open indices
        (expand_wildcards=open default); explicitly named closed indices
        raise unless the caller is an admin path (allow_closed)."""
        if expression in ("_all", "*", ""):
            return sorted(n for n in self.indices
                          if allow_closed
                          or not self.indices[n].is_closed)
        out = []
        import fnmatch
        for part in expression.split(","):
            part = part.strip()
            if not part:
                continue
            if self.name_resolver is not None and "*" not in part:
                resolved = self.name_resolver(part)
                if resolved is not None:
                    out.extend(resolved)
                    continue
            if "*" in part or "?" in part:
                matched = {n for n in self.indices
                           if fnmatch.fnmatch(n, part)
                           and (allow_closed
                                or not self.indices[n].is_closed)}
                # wildcards also expand over aliases/data streams (ref:
                # IndexNameExpressionResolver WildcardExpressionResolver)
                if self.abstraction_lister is not None:
                    for name, members in self.abstraction_lister().items():
                        if fnmatch.fnmatch(name, part):
                            matched.update(members)
                out.extend(sorted(matched))
            else:
                if part not in self.indices:
                    raise IndexNotFoundException(part)
                if self.indices[part].is_closed and not allow_closed:
                    from elasticsearch_tpu.common.errors import (
                        IndexClosedException)
                    raise IndexClosedException(part)
                out.append(part)
        seen = set()
        uniq = []
        for n in out:
            if n not in seen:
                seen.add(n)
                uniq.append(n)
        return uniq

    def close(self):
        for idx in self.indices.values():
            idx.close()
