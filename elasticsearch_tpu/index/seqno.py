"""Sequence numbers and checkpoints.

Mirrors the reference's seqno machinery (ref: index/seqno/
LocalCheckpointTracker.java, ReplicationTracker.java:80,159,616-638):
every operation gets a monotonically increasing sequence number; the local
checkpoint is the highest seqno below which *all* ops are processed; the
global checkpoint (multi-copy, in the replication layer) is the minimum
local checkpoint over in-sync copies. Retention leases keep history for
peer recovery.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Set

NO_OPS_PERFORMED = -1
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    """Tracks processed seqnos and computes the contiguous watermark."""

    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED,
                 local_checkpoint: int = NO_OPS_PERFORMED):
        self._lock = threading.Lock()
        self._next_seq_no = max_seq_no + 1
        self._checkpoint = local_checkpoint
        self._processed: Set[int] = set()

    def generate_seq_no(self) -> int:
        with self._lock:
            seq = self._next_seq_no
            self._next_seq_no += 1
            return seq

    def advance_max_seq_no(self, seq_no: int) -> None:
        """On replicas: ops arrive with pre-assigned seqnos."""
        with self._lock:
            if seq_no >= self._next_seq_no:
                self._next_seq_no = seq_no + 1

    def mark_seq_no_as_processed(self, seq_no: int) -> None:
        with self._lock:
            if seq_no <= self._checkpoint:
                return
            self._processed.add(seq_no)
            while self._checkpoint + 1 in self._processed:
                self._checkpoint += 1
                self._processed.remove(self._checkpoint)

    @property
    def checkpoint(self) -> int:
        return self._checkpoint

    @property
    def max_seq_no(self) -> int:
        return self._next_seq_no - 1

    def contains(self, seq_no: int) -> bool:
        with self._lock:
            return seq_no <= self._checkpoint or seq_no in self._processed


@dataclass
class RetentionLease:
    """ref: index/seqno/RetentionLease.java — a named guarantee that ops
    >= retaining_seq_no stay replayable (peer-recovery leases etc.)."""

    id: str
    retaining_seq_no: int
    timestamp: float
    source: str


@dataclass
class CheckpointState:
    """Per-copy state on the primary (ref: ReplicationTracker.CheckpointState)."""

    local_checkpoint: int = UNASSIGNED_SEQ_NO
    global_checkpoint: int = UNASSIGNED_SEQ_NO
    in_sync: bool = False
    tracked: bool = False


class ReplicationTracker:
    """Primary-side tracker of all shard copies: computes the global
    checkpoint = min(local checkpoint over in-sync copies) and manages
    retention leases (ref: index/seqno/ReplicationTracker.java:616-638
    computeGlobalCheckpoint)."""

    def __init__(self, shard_allocation_id: str,
                 local_checkpoint: int = NO_OPS_PERFORMED,
                 clock: Optional[Callable[[], float]] = None):
        self._lock = threading.Lock()
        # lease timestamps come from an injectable clock so the cluster
        # runtime can pin them to the scheduler's (virtual) time and
        # seeded chaos runs replay identically
        self._clock = clock or time.time
        self.allocation_id = shard_allocation_id
        self._checkpoints: Dict[str, CheckpointState] = {
            shard_allocation_id: CheckpointState(
                local_checkpoint=local_checkpoint, in_sync=True, tracked=True)
        }
        self._global_checkpoint = local_checkpoint
        self._leases: Dict[str, RetentionLease] = {}
        self.primary_mode = True

    # -- copy management
    def init_tracking(self, allocation_id: str) -> None:
        with self._lock:
            self._checkpoints.setdefault(allocation_id, CheckpointState(tracked=True))
            self._checkpoints[allocation_id].tracked = True

    def mark_in_sync(self, allocation_id: str, local_checkpoint: int) -> None:
        with self._lock:
            st = self._checkpoints.setdefault(allocation_id, CheckpointState())
            st.local_checkpoint = max(st.local_checkpoint, local_checkpoint)
            st.in_sync = True
            st.tracked = True
            self._recompute()

    def remove_copy(self, allocation_id: str) -> None:
        with self._lock:
            if allocation_id != self.allocation_id:
                self._checkpoints.pop(allocation_id, None)
                self._recompute()

    def update_local_checkpoint(self, allocation_id: str, checkpoint: int) -> None:
        with self._lock:
            st = self._checkpoints.get(allocation_id)
            if st is None:
                return
            if checkpoint > st.local_checkpoint:
                st.local_checkpoint = checkpoint
                self._recompute()

    def _recompute(self) -> None:
        in_sync = [s.local_checkpoint for s in self._checkpoints.values() if s.in_sync]
        if in_sync:
            gc = min(in_sync)
            if gc > self._global_checkpoint:
                self._global_checkpoint = gc

    @property
    def global_checkpoint(self) -> int:
        return self._global_checkpoint

    def in_sync_ids(self) -> Set[str]:
        with self._lock:
            return {a for a, s in self._checkpoints.items() if s.in_sync}

    def is_tracked(self, allocation_id: str) -> bool:
        with self._lock:
            st = self._checkpoints.get(allocation_id)
            return st is not None and st.tracked

    def tracked_ids(self) -> Set[str]:
        with self._lock:
            return {a for a, s in self._checkpoints.items() if s.tracked}

    def in_sync_checkpoints(self) -> Dict[str, int]:
        """Snapshot of {allocation_id: local_checkpoint} over the in-sync
        set — the state a primary-relocation handoff ships so the target
        can seed its own tracker (ref: ReplicationTracker
        getPrimaryContext / activateWithPrimaryContext)."""
        with self._lock:
            return {a: s.local_checkpoint
                    for a, s in self._checkpoints.items() if s.in_sync}

    # -- retention leases (ref: ReplicationTracker.java:511)
    def add_retention_lease(self, lease_id: str, retaining_seq_no: int,
                            source: str) -> RetentionLease:
        with self._lock:
            lease = RetentionLease(lease_id, retaining_seq_no,
                                   self._clock(), source)
            self._leases[lease_id] = lease
            return lease

    def renew_retention_lease(self, lease_id: str, retaining_seq_no: int) -> None:
        with self._lock:
            lease = self._leases[lease_id]
            lease.retaining_seq_no = max(lease.retaining_seq_no, retaining_seq_no)
            lease.timestamp = self._clock()

    def remove_retention_lease(self, lease_id: str) -> None:
        with self._lock:
            self._leases.pop(lease_id, None)

    def get_retention_leases(self) -> Dict[str, RetentionLease]:
        with self._lock:
            return dict(self._leases)

    def min_retained_seq_no(self) -> int:
        """History below this can be discarded."""
        with self._lock:
            if not self._leases:
                return self._global_checkpoint + 1
            return min(l.retaining_seq_no for l in self._leases.values())
