"""Index metadata: aliases, composable templates, data streams, rollover.

Mirrors the reference's cluster-metadata layer (ref: cluster/metadata/
Metadata.java — aliases in IndexAbstraction resolution,
MetadataIndexTemplateService for composable + component templates,
DataStream + MetadataCreateDataStreamService, MetadataRolloverService).
There it all lives in replicated cluster state; here it persists to the
node data path with the same observable API semantics.

Resolution order for a name (ref: IndexAbstraction lookup): concrete
index → data stream (its backing indices) → alias (its member indices) →
wildcard over all three.
"""

from __future__ import annotations

import fnmatch
import json
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    IndexNotFoundException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)

ROLLOVER_SUFFIX_RE = re.compile(r"^(.*)-(\d{6})$")


class MetadataService:
    def __init__(self, indices_service, data_path: Optional[str] = None):
        self.indices = indices_service
        # alias -> {index_name: {"filter": query?, "is_write_index": bool}}
        self.aliases: Dict[str, Dict[str, Dict[str, Any]]] = {}
        # composable index templates + component templates
        self.index_templates: Dict[str, Dict[str, Any]] = {}
        self.component_templates: Dict[str, Dict[str, Any]] = {}
        # data stream -> {"timestamp_field": ..., "indices": [...], "generation": N}
        self.data_streams: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._path = (os.path.join(data_path, "_metadata.json")
                      if data_path else None)
        if data_path:
            os.makedirs(data_path, exist_ok=True)
        if self._path and os.path.exists(self._path):
            with open(self._path) as fh:
                state = json.load(fh)
            self.aliases = state.get("aliases", {})
            self.index_templates = state.get("index_templates", {})
            self.component_templates = state.get("component_templates", {})
            self.data_streams = state.get("data_streams", {})
        # hook index-name resolution (search path goes through
        # IndicesService.resolve), wildcard expansion, and delete cleanup
        indices_service.name_resolver = self.indices_for
        indices_service.abstraction_lister = self._abstractions
        indices_service.delete_listeners.append(self._on_index_deleted)

    def _persist(self):
        if self._path:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"aliases": self.aliases,
                           "index_templates": self.index_templates,
                           "component_templates": self.component_templates,
                           "data_streams": self.data_streams}, fh)
            os.replace(tmp, self._path)

    # ------------------------------------------------------------ aliases
    def update_aliases(self, actions: List[Dict[str, Any]]) -> None:
        """ref: TransportIndicesAliasesAction — atomic batch of
        add/remove/remove_index actions."""
        with self._lock:
            staged = {a: dict(m) for a, m in self.aliases.items()}
            for action in actions:
                (kind, spec), = action.items()
                if kind == "add":
                    indices = self._action_indices(spec)
                    alias = spec.get("alias")
                    aliases = spec.get("aliases",
                                       [alias] if alias else [])
                    if isinstance(aliases, str):
                        aliases = [aliases]
                    if not aliases:
                        raise IllegalArgumentException(
                            "[add] requires an [alias] to be set")
                    for a in aliases:
                        if self.indices.has(a) or a in self.data_streams:
                            raise IllegalArgumentException(
                                f"alias [{a}] collides with an existing "
                                f"index or data stream")
                        entry = staged.setdefault(a, {})
                        for idx in indices:
                            props: Dict[str, Any] = {}
                            if "filter" in spec:
                                props["filter"] = spec["filter"]
                            if spec.get("is_write_index"):
                                props["is_write_index"] = True
                            entry[idx] = props
                elif kind == "remove":
                    indices = self._action_indices(spec)
                    alias = spec.get("alias")
                    aliases = spec.get("aliases", [alias] if alias else [])
                    if isinstance(aliases, str):
                        aliases = [aliases]
                    if not aliases:
                        raise IllegalArgumentException(
                            "[remove] requires an [alias] to be set")
                    removed_any = False
                    for a in list(staged):
                        if not any(fnmatch.fnmatch(a, pat)
                                   for pat in aliases):
                            continue
                        for idx in indices:
                            if idx in staged[a]:
                                del staged[a][idx]
                                removed_any = True
                        if not staged[a]:
                            del staged[a]
                    if not removed_any and spec.get("must_exist"):
                        raise ResourceNotFoundException(
                            f"aliases {aliases} missing")
                elif kind == "remove_index":
                    for idx in self._action_indices(spec):
                        self.indices.delete_index(idx)
                        for a in list(staged):
                            staged[a].pop(idx, None)
                            if not staged[a]:
                                del staged[a]
                else:
                    raise IllegalArgumentException(
                        f"unknown alias action [{kind}]")
            self.aliases = staged
            self._persist()

    def _action_indices(self, spec: Dict[str, Any]) -> List[str]:
        index = spec.get("index")
        indices = spec.get("indices", [index] if index else [])
        if isinstance(indices, str):
            indices = [indices]
        if not indices:
            raise IllegalArgumentException(
                "alias action requires an [index] to be set")
        out = []
        for pat in indices:
            if "*" in pat:
                out.extend(n for n in sorted(self.indices.indices)
                           if fnmatch.fnmatch(n, pat))
            else:
                if not self.indices.has(pat):
                    raise IndexNotFoundException(pat)
                out.append(pat)
        return out

    def get_aliases(self, index: Optional[str] = None,
                    alias: Optional[str] = None) -> Dict[str, Any]:
        """GET _alias shape: {index: {"aliases": {alias: props}}}."""
        out: Dict[str, Any] = {}
        for a, members in self.aliases.items():
            if alias and not fnmatch.fnmatch(a, alias):
                continue
            for idx, props in members.items():
                if index and not fnmatch.fnmatch(idx, index):
                    continue
                out.setdefault(idx, {"aliases": {}})["aliases"][a] = props
        if index and not out and index != "*" and "*" not in index:
            if not self.indices.has(index):
                raise IndexNotFoundException(index)
            out[index] = {"aliases": {}}
        return out

    def alias_filter(self, name: str) -> Optional[Dict[str, Any]]:
        """The (single) filter if ``name`` is a filtered alias — applied as
        an extra bool filter by the search layer (ref: AliasFilter)."""
        members = self.aliases.get(name)
        if not members:
            return None
        filters = [p["filter"] for p in members.values() if "filter" in p]
        if not filters:
            return None
        if len(filters) == 1:
            return filters[0]
        return {"bool": {"should": filters, "minimum_should_match": 1}}

    def _abstractions(self) -> Dict[str, List[str]]:
        out = {a: sorted(m) for a, m in self.aliases.items()}
        out.update({ds: list(meta["indices"])
                    for ds, meta in self.data_streams.items()})
        return out

    def _on_index_deleted(self, name: str) -> None:
        """Keep aliases/data streams consistent when an index is deleted
        out from under them (ref: MetadataDeleteIndexService strips the
        index from every alias and backing list)."""
        with self._lock:
            changed = False
            for a in list(self.aliases):
                if name in self.aliases[a]:
                    del self.aliases[a][name]
                    changed = True
                    if not self.aliases[a]:
                        del self.aliases[a]
            for ds in list(self.data_streams):
                meta = self.data_streams[ds]
                if name in meta["indices"]:
                    meta["indices"].remove(name)
                    changed = True
                    if not meta["indices"]:
                        del self.data_streams[ds]
            if changed:
                self._persist()

    # --------------------------------------------------------- resolution
    def indices_for(self, name: str) -> Optional[List[str]]:
        """Resolver hook for IndicesService: alias/data-stream names →
        concrete indices; None → not ours (concrete index or missing)."""
        if name in self.data_streams:
            return list(self.data_streams[name]["indices"])
        if name in self.aliases:
            return sorted(self.aliases[name])
        return None

    def write_target(self, name: str) -> str:
        """Concrete index a write to ``name`` lands in (ref:
        IndexAbstraction.getWriteIndex)."""
        if name in self.data_streams:
            return self.data_streams[name]["indices"][-1]
        members = self.aliases.get(name)
        if members:
            writes = [i for i, p in members.items()
                      if p.get("is_write_index")]
            if len(writes) == 1:
                return writes[0]
            if len(members) == 1:
                return next(iter(members))
            raise IllegalArgumentException(
                f"no write index is defined for alias [{name}]")
        return name

    # ---------------------------------------------------------- templates
    def put_component_template(self, name: str, body: Dict[str, Any]):
        if "template" not in body:
            raise IllegalArgumentException(
                "[template] is required for a component template")
        with self._lock:
            self.component_templates[name] = body
            self._persist()

    def put_index_template(self, name: str, body: Dict[str, Any]):
        patterns = body.get("index_patterns")
        if not patterns:
            raise IllegalArgumentException(
                "[index_patterns] is required for an index template")
        for c in body.get("composed_of", []):
            if c not in self.component_templates:
                raise IllegalArgumentException(
                    f"component template [{c}] does not exist")
        with self._lock:
            self.index_templates[name] = body
            self._persist()

    def delete_index_template(self, name: str):
        if name not in self.index_templates:
            raise ResourceNotFoundException(
                f"index template [{name}] does not exist")
        del self.index_templates[name]
        self._persist()

    def delete_component_template(self, name: str):
        if name not in self.component_templates:
            raise ResourceNotFoundException(
                f"component template [{name}] does not exist")
        del self.component_templates[name]
        self._persist()

    def match_template(self, index_name: str) -> Optional[Dict[str, Any]]:
        """Highest-priority matching composable template, with its
        component templates merged in order then the template itself
        (ref: MetadataIndexTemplateService.resolveTemplate)."""
        best = None
        best_prio = -1
        best_name = None
        for name, tmpl in self.index_templates.items():
            pats = tmpl["index_patterns"]
            if isinstance(pats, str):
                pats = [pats]
            if any(fnmatch.fnmatch(index_name, p) for p in pats):
                prio = int(tmpl.get("priority", 0))
                if prio > best_prio:
                    best, best_prio, best_name = tmpl, prio, name
        if best is None:
            return None
        merged: Dict[str, Any] = {"settings": {}, "mappings": {},
                                  "aliases": {}}
        for comp in best.get("composed_of", []):
            self._merge_template(merged,
                                 self.component_templates[comp]["template"])
        self._merge_template(merged, best.get("template", {}))
        merged["_name"] = best_name
        merged["_data_stream"] = best.get("data_stream")
        return merged

    @staticmethod
    def _merge_template(acc: Dict[str, Any], tmpl: Dict[str, Any]):
        acc["settings"].update(tmpl.get("settings", {}))
        _deep_update(acc["mappings"], tmpl.get("mappings", {}))
        acc["aliases"].update(tmpl.get("aliases", {}))

    def create_index_from_template(self, name: str,
                                   body: Optional[Dict[str, Any]] = None):
        """Create an index applying any matching template, then the
        request body on top (request wins)."""
        body = body or {}
        if name in self.aliases or name in self.data_streams:
            raise IllegalArgumentException(
                f"index name [{name}] collides with an existing alias or "
                f"data stream")
        tmpl = self.match_template(name) or {"settings": {}, "mappings": {},
                                             "aliases": {}}
        settings = dict(tmpl["settings"])
        settings.update(body.get("settings", {}))
        mappings = {}
        _deep_update(mappings, tmpl["mappings"])
        _deep_update(mappings, body.get("mappings", {}))
        idx = self.indices.create_index(name, settings or None,
                                        mappings or None)
        alias_actions = []
        for a, props in {**tmpl["aliases"],
                         **body.get("aliases", {})}.items():
            spec = {"index": name, "alias": a}
            spec.update(props or {})
            alias_actions.append({"add": spec})
        if alias_actions:
            self.update_aliases(alias_actions)
        return idx

    # -------------------------------------------------------- data streams
    def create_data_stream(self, name: str) -> None:
        """ref: MetadataCreateDataStreamService — requires a matching
        template with a data_stream object."""
        with self._lock:
            if name in self.data_streams:
                raise ResourceAlreadyExistsException(
                    f"data_stream [{name}] already exists")
            if self.indices.has(name) or name in self.aliases:
                raise IllegalArgumentException(
                    f"data stream name [{name}] collides with an existing "
                    f"index or alias")
            tmpl = self.match_template(name)
            if tmpl is None or tmpl.get("_data_stream") is None:
                raise IllegalArgumentException(
                    f"no matching index template with a data_stream "
                    f"definition for [{name}]")
            backing = self._backing_name(name, 1)
            mappings = {"properties": {"@timestamp": {"type": "date"}}}
            _deep_update(mappings, tmpl["mappings"])
            self.indices.create_index(backing, tmpl["settings"] or None,
                                      mappings)
            self.data_streams[name] = {
                "timestamp_field": "@timestamp",
                "indices": [backing],
                "generation": 1,
            }
            self._persist()

    @staticmethod
    def _backing_name(stream: str, generation: int) -> str:
        stamp = time.strftime("%Y.%m.%d", time.gmtime())
        return f".ds-{stream}-{stamp}-{generation:06d}"

    def get_data_streams(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        out = []
        for ds, meta in sorted(self.data_streams.items()):
            if name and name not in ("*", "_all") and \
                    not fnmatch.fnmatch(ds, name):
                continue
            out.append({
                "name": ds,
                "timestamp_field": {"name": meta["timestamp_field"]},
                "indices": [{"index_name": n} for n in meta["indices"]],
                "generation": meta["generation"],
                "status": "GREEN",
            })
        return out

    def delete_data_stream(self, name: str) -> None:
        with self._lock:
            if name not in self.data_streams:
                raise ResourceNotFoundException(
                    f"data_stream [{name}] does not exist")
            for backing in self.data_streams[name]["indices"]:
                if self.indices.has(backing):
                    self.indices.delete_index(backing)
            del self.data_streams[name]
            self._persist()

    # ------------------------------------------------------------ rollover
    def rollover(self, target: str,
                 body: Optional[Dict[str, Any]] = None,
                 dry_run: bool = False) -> Dict[str, Any]:
        """ref: MetadataRolloverService — conditions checked against the
        current write index; on rollover a successor index is created and
        the alias/data-stream flips to it."""
        body = body or {}
        conditions = body.get("conditions", {})
        with self._lock:
            if target in self.data_streams:
                ds = self.data_streams[target]
                old_index = ds["indices"][-1]
                new_gen = ds["generation"] + 1
                new_index = self._backing_name(target, new_gen)
                is_stream = True
            elif target in self.aliases:
                old_index = self.write_target(target)
                m = ROLLOVER_SUFFIX_RE.match(old_index)
                if body.get("new_index"):
                    new_index = body["new_index"]
                elif m:
                    new_index = f"{m.group(1)}-{int(m.group(2)) + 1:06d}"
                else:
                    raise IllegalArgumentException(
                        f"index name [{old_index}] does not match pattern "
                        f"'^.*-\\d+$' — specify [new_index]")
                is_stream = False
            else:
                raise IllegalArgumentException(
                    f"rollover target [{target}] is not an alias or data "
                    f"stream")

            met = self._check_conditions(old_index, conditions)
            should_roll = (not conditions) or any(met.values())
            result = {
                "old_index": old_index, "new_index": new_index,
                "rolled_over": False, "dry_run": dry_run,
                "acknowledged": True, "conditions": met,
            }
            if dry_run or not should_roll:
                return result
            if is_stream:
                tmpl = self.match_template(target) or {
                    "settings": {}, "mappings": {}}
                mappings = {"properties": {"@timestamp": {"type": "date"}}}
                _deep_update(mappings, tmpl.get("mappings", {}))
                self.indices.create_index(new_index,
                                          tmpl.get("settings") or None,
                                          mappings)
                ds["indices"].append(new_index)
                ds["generation"] = new_gen
            else:
                self.create_index_from_template(
                    new_index, {k: v for k, v in body.items()
                                if k in ("settings", "mappings", "aliases")})
                members = self.aliases[target]
                old_props = members.get(old_index, {})
                if old_props.get("is_write_index"):
                    # explicit write alias: old index stays as a read
                    # member (ref: MetadataRolloverService)
                    members[old_index] = {
                        k: v for k, v in old_props.items()
                        if k != "is_write_index"}
                else:
                    # implicit single-index alias swaps entirely
                    members.pop(old_index, None)
                members[new_index] = {"is_write_index": True}
            self._persist()
            result["rolled_over"] = True
            return result

    def _check_conditions(self, index_name: str,
                          conditions: Dict[str, Any]) -> Dict[str, bool]:
        met: Dict[str, bool] = {}
        if not conditions:
            return met
        idx = self.indices.get(index_name)
        stats = idx.stats()
        doc_count = stats["docs"]["count"]
        if "max_docs" in conditions:
            met[f"[max_docs: {conditions['max_docs']}]"] = (
                doc_count >= int(conditions["max_docs"]))
        if "max_age" in conditions:
            # index creation time from the data dir mtime
            age_s = time.time() - os.path.getctime(idx.path)
            met[f"[max_age: {conditions['max_age']}]"] = (
                age_s * 1000 >= _parse_ms(conditions["max_age"]))
        if "max_size" in conditions:
            size = sum(seg.ram_bytes() for sh in idx.shards
                       for seg in sh.segments)
            met[f"[max_size: {conditions['max_size']}]"] = (
                size >= _parse_bytes(conditions["max_size"]))
        return met


# ---------------------------------------------------------------------------
# shrink / split (host-side columnar reshard)
# ---------------------------------------------------------------------------

def resize_index(indices_service, source_name: str, target_name: str,
                 body: Optional[Dict[str, Any]] = None,
                 mode: str = "shrink"):
    """ref: action/admin/indices/shrink/ (TransportResizeAction). The
    reference hard-links Lucene files and re-filters; here the columnar
    segments are re-partitioned host-side by the same routing hash — an
    honest equivalent at this segment format, and the device re-uploads
    lazily per new shard."""
    body = body or {}
    src = indices_service.get(source_name)
    # buffered (unrefreshed) docs must be in the published segments before
    # the copy, or the resized index silently loses them
    src.refresh()
    settings = dict(body.get("settings", {}))
    n_target = int(settings.get(
        "index.number_of_shards",
        1 if mode == "shrink"
        else src.num_shards if mode == "clone"
        else src.num_shards * 2))
    if mode == "shrink" and n_target > src.num_shards:
        raise IllegalArgumentException(
            f"the number of target shards [{n_target}] must be less than or "
            f"equal to the number of source shards [{src.num_shards}]")
    if mode == "clone" and n_target != src.num_shards:
        raise IllegalArgumentException(
            f"the number of target shards [{n_target}] must be the "
            f"same as the number of source shards [{src.num_shards}]")
    if mode == "split" and n_target < src.num_shards:
        raise IllegalArgumentException(
            f"the number of target shards [{n_target}] must be greater than "
            f"the number of source shards [{src.num_shards}]")
    # the source's write block (set before a resize, ref: ResizeRequest
    # requires a read-only source) must not be inherited DURING the copy —
    # explicitly requested blocks apply after the docs land
    merged_settings = {k: v for k, v in src.settings.as_dict().items()
                       if not k.startswith("index.blocks.")
                       and k != "index.state"}
    merged_settings.update({k: v for k, v in settings.items()
                            if not k.startswith("index.blocks.")})
    merged_settings["index.number_of_shards"] = n_target
    target = indices_service.create_index(
        target_name, merged_settings, src.mapper.to_mapping())
    for engine in src.shards:
        for seg in engine.segments:
            for docid in range(seg.n_docs):
                if not seg.live[docid]:
                    continue
                doc_id = seg.stored.ids[docid]
                source = json.loads(seg.stored.source(docid))
                target.index_doc(doc_id, source)
    target.refresh()
    target.flush()
    requested_blocks = {k: v for k, v in settings.items()
                        if k.startswith("index.blocks.")}
    if requested_blocks:
        target.update_settings(requested_blocks)
    return target


def _deep_update(base: Dict[str, Any], update: Dict[str, Any]):
    for k, v in update.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_update(base[k], v)
        else:
            base[k] = v


def _parse_ms(v) -> float:
    units = {"ms": 1.0, "s": 1000.0, "m": 60_000.0, "h": 3_600_000.0,
             "d": 86_400_000.0}
    s = str(v)
    for suffix in ("ms", "s", "m", "h", "d"):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    return float(s)


def _parse_bytes(v) -> float:
    units = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3, "tb": 1024**4}
    s = str(v).lower()
    for suffix in ("kb", "mb", "gb", "tb", "b"):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    return float(s)
