"""The engine: versioned writes over immutable segments + WAL.

Mirrors the reference's InternalEngine (ref: index/engine/
InternalEngine.java:831-910 — per-op versioning plan → index into Lucene →
translog append; LiveVersionMap for realtime GET; refresh publishes
near-real-time readers; flush = commit + translog roll). TPU re-design:

- The indexing buffer is a list of parsed docs; **refresh** builds an
  immutable columnar segment (index/segment.py) and atomically swaps the
  published segment list — the epoch-pointer swap that maps directly to
  swapping device-resident segment sets in HBM (SURVEY.md §7 stage 4).
- Updates/deletes of already-refreshed docs flip the target segment's live
  mask (soft deletes as masks); in-buffer updates tombstone the buffered doc.
- **flush** persists segments + a commit point, rolls the translog
  generation, trims old generations. Crash recovery = load commit point,
  replay newer translog ops.
- A merge policy folds small segments together (ref:
  ElasticsearchConcurrentMergeScheduler / TieredMergePolicy, simplified).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    EngineClosedException,
    VersionConflictEngineException,
)
from elasticsearch_tpu.index.mapper import MapperService, ParsedDocument
from elasticsearch_tpu.index.segment import Segment, SegmentWriter, merge_segments
from elasticsearch_tpu.index.seqno import LocalCheckpointTracker, NO_OPS_PERFORMED
from elasticsearch_tpu.index.translog import Translog, TranslogOp

# shard-path-prefix -> materializer(shard_path, seg_name) -> bool, set by
# the node container when repositories exist (searchable snapshots —
# keyed by data path so multiple in-process nodes stay independent)
LAZY_MATERIALIZERS: Dict[str, Any] = {}


def _find_materializer(shard_path: str):
    for prefix, fn in LAZY_MATERIALIZERS.items():
        # prefix + separator: "/data/node1" must not claim
        # "/data/node10/..." shards
        if shard_path.startswith(prefix.rstrip(os.sep) + os.sep):
            return fn
    return None


@dataclass
class VersionValue:
    """LiveVersionMap entry (ref: index/engine/LiveVersionMap.java)."""

    version: int
    seq_no: int
    primary_term: int
    deleted: bool = False
    buffer_idx: int = -1  # >= 0 while the doc lives in the indexing buffer


@dataclass
class IndexResult:
    doc_id: str
    version: int
    seq_no: int
    primary_term: int
    created: bool


@dataclass
class DeleteResult:
    doc_id: str
    version: int
    seq_no: int
    primary_term: int
    found: bool


@dataclass
class GetResult:
    found: bool
    doc_id: str
    source: Optional[Dict[str, Any]] = None
    version: int = -1
    seq_no: int = -1
    primary_term: int = -1


class SearcherSnapshot:
    """Point-in-time view over the published segments (the analogue of an
    acquired Lucene searcher, ref: IndexShard.java:1215-1230). Holds the
    segment list + the live-mask versions seen at acquisition."""

    def __init__(self, segments: List[Segment], epoch: int):
        self.segments = list(segments)
        self.epoch = epoch

    @property
    def doc_count(self) -> int:
        return sum(s.live_doc_count for s in self.segments)


class Engine:
    def __init__(self, shard_path: str, mapper_service: MapperService,
                 merge_factor: int = 10):
        self.path = shard_path
        self.mapper = mapper_service
        self.merge_factor = merge_factor
        os.makedirs(shard_path, exist_ok=True)
        self._lock = threading.RLock()
        self._closed = False
        self.primary_term = 1
        self.tracker = LocalCheckpointTracker()
        self.version_map: Dict[str, VersionValue] = {}
        self._buffer: List[Tuple[str, ParsedDocument]] = []
        self._buffer_dead: set = set()
        # keyed (segment name, docid) to dedupe repeated tombstones pre-refresh
        self._pending_tombstones: Dict[Tuple[str, int], Tuple[Segment, int]] = {}
        self._segments: List[Segment] = []
        # committed segments whose files are snapshot-backed and not yet
        # fetched (searchable snapshots — materialized on first search)
        self._deferred_segments: List[str] = []
        self._dirty_segments: set = set()   # names needing (re)save
        self._epoch = 0                      # bumps on every refresh/delete
        self._seg_counter = 0
        # segment names must be GLOBALLY unique (device caches key on them
        # across shards/indices), so prefix with a per-engine-instance uuid
        self._seg_prefix = uuid.uuid4().hex[:12]
        self.translog = Translog(os.path.join(shard_path, "translog"))
        self._recover()

    # ------------------------------------------------------------ recovery
    def _commit_path(self) -> str:
        return os.path.join(self.path, "segments.json")

    def _recover(self) -> None:
        commit_gen = 1
        if os.path.exists(self._commit_path()):
            with open(self._commit_path()) as fh:
                commit = json.load(fh)
            lazy_manifest = os.path.exists(
                os.path.join(self.path, "snapshot_store.json"))
            for name in commit["segments"]:
                seg_dir = os.path.join(self.path, name)
                complete = all(
                    os.path.exists(os.path.join(seg_dir, f))
                    for f in ("meta.json", "arrays.npz", "stored.bin"))
                if lazy_manifest and not complete:
                    # snapshot-mounted shard: files stream in lazily on
                    # first search (ref: SearchableSnapshotDirectory —
                    # mounting costs no local data until queried). A
                    # PARTIAL dir (crash mid-materialize) re-defers too:
                    # materialization refetches whatever is missing.
                    self._deferred_segments.append(name)
                    continue
                seg = Segment.load(seg_dir)
                self._segments.append(seg)
            commit_gen = commit["translog_generation"]
            self.primary_term = commit.get("primary_term", 1)
            self._seg_counter = commit.get("seg_counter", len(self._segments))
            max_seq = commit.get("max_seq_no", NO_OPS_PERFORMED)
            self.tracker = LocalCheckpointTracker(max_seq, max_seq)
            # rebuild version map from the persisted metadata doc values
            for seg in self._segments:
                seq_nv = seg.numerics.get("_seq_no")
                term_nv = seg.numerics.get("_primary_term")
                ver_nv = seg.numerics.get("_version")
                for docid, doc_id in enumerate(seg.stored.ids):
                    if seg.live[docid]:
                        self.version_map[doc_id] = VersionValue(
                            version=int(ver_nv.values[docid]) if ver_nv is not None else 1,
                            seq_no=int(seq_nv.values[docid]) if seq_nv is not None else NO_OPS_PERFORMED,
                            primary_term=int(term_nv.values[docid]) if term_nv is not None else self.primary_term)
        # replay translog ops recorded after the commit point
        # (ref: InternalEngine.recoverFromTranslog)
        for op in self.translog.read_ops(commit_gen):
            if op.op_type == "index":
                self._index_internal(op.doc_id, op.source, seq_no=op.seq_no,
                                     primary_term=op.primary_term,
                                     from_translog=True)
            elif op.op_type == "delete":
                self._delete_internal(op.doc_id, seq_no=op.seq_no,
                                      primary_term=op.primary_term,
                                      from_translog=True)
            self.tracker.advance_max_seq_no(op.seq_no)
            self.tracker.mark_seq_no_as_processed(op.seq_no)

    # ------------------------------------------------------------- writes
    def _check_open(self):
        if self._closed:
            raise EngineClosedException("engine is closed")

    def index(self, doc_id: str, source: Dict[str, Any],
              seq_no: Optional[int] = None, primary_term: Optional[int] = None,
              if_seq_no: Optional[int] = None,
              if_primary_term: Optional[int] = None,
              op_type: str = "index") -> IndexResult:
        """ref: InternalEngine.index:831 — plan (version/CAS checks) →
        index into the buffer → translog append."""
        with self._lock:
            self._check_open()
            existing = self.version_map.get(doc_id)
            exists = existing is not None and not existing.deleted
            # CAS (if_seq_no/if_primary_term, ref: compare-and-set on seqno)
            if if_seq_no is not None or if_primary_term is not None:
                if not exists:
                    raise VersionConflictEngineException(
                        doc_id, "document does not exist")
                if (existing.seq_no != if_seq_no or
                        existing.primary_term != if_primary_term):
                    raise VersionConflictEngineException(
                        doc_id,
                        f"required seqNo [{if_seq_no}], primary term "
                        f"[{if_primary_term}]. current document has seqNo "
                        f"[{existing.seq_no}] and primary term "
                        f"[{existing.primary_term}]")
            if op_type == "create" and exists:
                raise VersionConflictEngineException(
                    doc_id, "document already exists")
            result = self._index_internal(
                doc_id, source, seq_no=seq_no, primary_term=primary_term)
            self.translog.add(TranslogOp(
                "index", result.seq_no, result.primary_term,
                doc_id=doc_id, source=source, version=result.version))
            return result

    def _index_internal(self, doc_id, source, seq_no=None, primary_term=None,
                        from_translog=False) -> IndexResult:
        if seq_no is None:
            seq_no = self.tracker.generate_seq_no()
        else:
            self.tracker.advance_max_seq_no(seq_no)
        if primary_term is None:
            primary_term = self.primary_term
        existing = self.version_map.get(doc_id)
        exists = existing is not None and not existing.deleted
        version = existing.version + 1 if exists else 1
        created = not exists
        self._remove_current_doc(doc_id, existing)
        parsed = self.mapper.parse(doc_id, source)
        # persist seqno/term/version as metadata doc values so CAS state
        # survives restart (ref: SeqNoFieldMapper/VersionFieldMapper —
        # _seq_no and _version are indexed per doc)
        parsed.numeric_values["_seq_no"] = [float(seq_no)]
        parsed.numeric_values["_primary_term"] = [float(primary_term)]
        parsed.numeric_values["_version"] = [float(version)]
        self._buffer.append((doc_id, parsed))
        self.version_map[doc_id] = VersionValue(
            version=version, seq_no=seq_no, primary_term=primary_term,
            buffer_idx=len(self._buffer) - 1)
        if not from_translog:
            self.tracker.mark_seq_no_as_processed(seq_no)
        return IndexResult(doc_id, version, seq_no, primary_term, created)

    def delete(self, doc_id: str,
               seq_no: Optional[int] = None,
               primary_term: Optional[int] = None,
               if_seq_no: Optional[int] = None,
               if_primary_term: Optional[int] = None) -> DeleteResult:
        """seq_no/primary_term pre-assigned on the replica/replay path
        (like index(); ref: IndexShard.applyDeleteOperationOnReplica)."""
        with self._lock:
            self._check_open()
            existing = self.version_map.get(doc_id)
            exists = existing is not None and not existing.deleted
            if if_seq_no is not None and (
                    not exists or existing.seq_no != if_seq_no or
                    existing.primary_term != if_primary_term):
                raise VersionConflictEngineException(
                    doc_id, f"required seqNo [{if_seq_no}]")
            result = self._delete_internal(doc_id, seq_no=seq_no,
                                           primary_term=primary_term)
            self.translog.add(TranslogOp(
                "delete", result.seq_no, result.primary_term, doc_id=doc_id))
            return result

    def _delete_internal(self, doc_id, seq_no=None, primary_term=None,
                         from_translog=False) -> DeleteResult:
        if seq_no is None:
            seq_no = self.tracker.generate_seq_no()
        else:
            self.tracker.advance_max_seq_no(seq_no)
        if primary_term is None:
            primary_term = self.primary_term
        existing = self.version_map.get(doc_id)
        found = existing is not None and not existing.deleted
        version = existing.version + 1 if existing else 1
        self._remove_current_doc(doc_id, existing)
        self.version_map[doc_id] = VersionValue(
            version=version, seq_no=seq_no, primary_term=primary_term,
            deleted=True)
        if not from_translog:
            self.tracker.mark_seq_no_as_processed(seq_no)
        return DeleteResult(doc_id, version, seq_no, primary_term, found)

    def _remove_current_doc(self, doc_id: str, existing: Optional[VersionValue]):
        """Tombstone the current copy of a doc, wherever it lives. Segment
        tombstones are DEFERRED to the next refresh so the previous version
        stays searchable until then (ES NRT semantics: neither updates nor
        deletes are visible to search before a refresh)."""
        if existing is not None and existing.buffer_idx >= 0:
            self._buffer_dead.add(existing.buffer_idx)
            return
        for seg in self._segments:
            docid = seg.docid_for(doc_id)
            if docid >= 0:
                self._pending_tombstones[(seg.name, docid)] = (seg, docid)
                return

    # -------------------------------------------------------------- reads
    def get(self, doc_id: str) -> GetResult:
        """Realtime GET (ref: LiveVersionMap + translog realtime get,
        index/shard/IndexShard.java:926): buffered docs are visible before
        any refresh."""
        with self._lock:
            vv = self.version_map.get(doc_id)
            if vv is None or vv.deleted:
                return GetResult(False, doc_id)
            if vv.buffer_idx >= 0:
                _, parsed = self._buffer[vv.buffer_idx]
                return GetResult(True, doc_id, json.loads(parsed.source),
                                 vv.version, vv.seq_no, vv.primary_term)
            for seg in self._segments:
                docid = seg.docid_for(doc_id)
                if docid >= 0:
                    return GetResult(True, doc_id,
                                     json.loads(seg.stored.source(docid)),
                                     vv.version, vv.seq_no, vv.primary_term)
            return GetResult(False, doc_id)

    def acquire_searcher(self) -> SearcherSnapshot:
        if self._deferred_segments:
            self._materialize_deferred()
        with self._lock:
            return SearcherSnapshot(self._segments, self._epoch)

    def _materialize_deferred(self) -> None:
        """Fetch snapshot-backed segments through the node's blob cache
        and publish them (the lazy-load moment of a mounted shard)."""
        fn = _find_materializer(self.path)
        with self._lock:
            names = list(self._deferred_segments)
        if not names:
            return
        loaded = []
        for name in names:
            if fn is None or not fn(self.path, name):
                raise IOError(
                    f"segment [{name}] is snapshot-backed but no "
                    f"repository materializer is registered")
            loaded.append(Segment.load(os.path.join(self.path, name)))
        with self._lock:
            if self._deferred_segments:
                self._segments = self._segments + loaded
                self._deferred_segments = []
                self._epoch += 1

    # ------------------------------------------------------ refresh/flush
    def refresh(self) -> bool:
        """Publish buffered docs as a new immutable segment (epoch swap).
        Returns True if a new segment was published."""
        with self._lock:
            self._check_open()
            changed = False
            if self._pending_tombstones:
                for seg, docid in self._pending_tombstones.values():
                    seg.delete(docid)
                    self._dirty_segments.add(seg.name)
                self._pending_tombstones = {}
                changed = True
            if not self._buffer:
                if changed:
                    self._epoch += 1
                    self._maybe_merge()
                return False
            writer = SegmentWriter()
            kept_ids = []
            for idx, (doc_id, parsed) in enumerate(self._buffer):
                if idx not in self._buffer_dead:
                    writer.add(parsed)
                    kept_ids.append(doc_id)
            published = False
            if len(writer):
                name = f"seg_{self._seg_prefix}_{self._seg_counter}"
                self._seg_counter += 1
                seg = writer.build(name)
                self._segments = self._segments + [seg]
                self._dirty_segments.add(name)
                published = True
            for doc_id in kept_ids:
                vv = self.version_map.get(doc_id)
                if vv is not None:
                    vv.buffer_idx = -1
            self._buffer = []
            self._buffer_dead = set()
            self._epoch += 1
            self._maybe_merge()
            return published

    def _maybe_merge(self) -> None:
        """Fold the smallest segments when too many accumulate."""
        if len(self._segments) <= self.merge_factor:
            return
        by_size = sorted(self._segments, key=lambda s: s.live_doc_count)
        to_merge = by_size[: len(self._segments) - self.merge_factor + 1]
        self.force_merge_segments(to_merge)

    def force_merge(self, max_num_segments: int = 1) -> None:
        """ref: forcemerge API."""
        with self._lock:
            self.refresh()
            if len(self._segments) > max_num_segments:
                self.force_merge_segments(list(self._segments))

    def force_merge_segments(self, to_merge: List[Segment]) -> None:
        name = f"seg_{self._seg_prefix}_{self._seg_counter}"
        self._seg_counter += 1
        merged = merge_segments(name, to_merge)
        merge_set = {s.name for s in to_merge}
        self._segments = [s for s in self._segments
                          if s.name not in merge_set] + [merged]
        self._dirty_segments -= merge_set
        self._dirty_segments.add(name)
        self._epoch += 1

    def flush(self) -> None:
        """refresh + persist segments + commit point + translog roll
        (ref: InternalEngine.flush = Lucene commit + translog roll)."""
        with self._lock:
            self._check_open()
            self.refresh()
            for seg in self._segments:
                if seg.name in self._dirty_segments:
                    seg.save(os.path.join(self.path, seg.name))
            self._dirty_segments = set()
            self.translog.sync()
            new_gen = self.translog.roll_generation()
            commit = {
                # still-deferred snapshot-backed segments MUST stay in
                # the commit — dropping them would silently lose the
                # mounted data on the next open
                "segments": ([s.name for s in self._segments]
                             + list(self._deferred_segments)),
                "translog_generation": new_gen,
                "max_seq_no": self.tracker.max_seq_no,
                "local_checkpoint": self.tracker.checkpoint,
                "primary_term": self.primary_term,
                "seg_counter": self._seg_counter,
            }
            tmp = self._commit_path() + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(commit, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self._commit_path())
            self.translog.trim_generations(new_gen)

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "docs": {"count": sum(s.live_doc_count for s in self._segments)
                         + len(self._buffer) - len(self._buffer_dead)
                         - len(self._pending_tombstones),
                         "deleted": sum(s.n_docs - s.live_doc_count
                                        for s in self._segments)},
                "segments": {"count": len(self._segments)},
                "translog": self.translog.stats(),
                "seq_no": {"max_seq_no": self.tracker.max_seq_no,
                           "local_checkpoint": self.tracker.checkpoint},
            }

    @property
    def segments(self) -> List[Segment]:
        return self._segments

    @property
    def epoch(self) -> int:
        return self._epoch

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self.translog.close()
