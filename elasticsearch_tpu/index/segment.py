"""Immutable TPU-oriented segments — the Lucene replacement.

Design (replaces Lucene's postings/doc-values/stored-fields formats, ref:
SURVEY.md §7 stage 2; consumed by the kernels in ``ops/``):

- **Postings as padded blocks.** Each text/keyword field's postings are
  concatenated into fixed-size blocks of ``BLOCK_SIZE`` (128 = TPU lane
  width): ``block_docids[num_blocks, 128] int32`` and
  ``block_tfs[num_blocks, 128] float32``. Padding entries carry ``tf = 0``
  and ``docid = 0`` — a zero term frequency contributes exactly 0 BM25
  score, so padded lanes scatter harmlessly instead of needing masks.
  Per-term views are ``term_block_start/term_block_count`` ranges; a term's
  first/last blocks are padded rather than shared with neighbours, so block
  gathers by term never mix terms.
- **Block-max metadata** for WAND-style pruning on device:
  ``block_max_tf`` and ``block_min_len`` give an upper bound
  ``idf * max_tf / (max_tf + k1*(1-b+b*min_len/avg_len))`` per block —
  score is monotonic ↑ in tf and ↓ in doc length, so the bound is exact
  (ref: Lucene block-max WAND, TopDocsCollectorContext.java:210-217;
  here blocks are pruned coarsely then scored densely, SURVEY.md §7
  "hard parts" #1).
- **Columnar doc values**: float64 column per numeric field + missing mask;
  ordinal column per keyword field (sorted-term ordinals, the analogue of
  Lucene SortedSetDocValues) for aggregations/sorting.
- **Dense vector slab**: ``[n_docs, dims] float32`` per vector field,
  cast to bf16 at device upload; brute-force kNN is a tiled matmul on MXU.
- **Stored fields**: `_source` bytes with offsets; `_id` both stored and
  hash-mapped for realtime get.
- **Deletes as masks**: ``live[n_docs] bool`` — the device analogue of
  Lucene liveDocs, applied as a score mask (ref: soft-deletes,
  index/engine/InternalEngine.java).

Docids are segment-local dense int32. Search-time doc addressing is
(segment_idx, local_docid), mirroring Lucene's per-leaf docids.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

# on-disk segment format generation (ref: Lucene's per-codec versioning;
# bumped on any layout change, with loaders kept for older generations —
# the rolling-upgrade/full-cluster-restart contract, qa/rolling-upgrade/)
SEGMENT_FORMAT_VERSION = 1

BLOCK_SIZE = 128  # TPU lane width


# ---------------------------------------------------------------------------
# Per-field structures
# ---------------------------------------------------------------------------

@dataclass
class PostingsField:
    """Inverted index for one field, in padded-block layout."""

    field: str
    terms: List[str]                      # sorted
    doc_freq: np.ndarray                  # int32 [num_terms]
    total_term_freq: np.ndarray           # int64 [num_terms]
    term_block_start: np.ndarray          # int32 [num_terms]
    term_block_count: np.ndarray          # int32 [num_terms]
    block_docids: np.ndarray              # int32 [num_blocks, BLOCK_SIZE]
    block_tfs: np.ndarray                 # float32 [num_blocks, BLOCK_SIZE]
    block_max_tf: np.ndarray              # float32 [num_blocks]
    block_min_len: np.ndarray             # float32 [num_blocks]
    field_lengths: np.ndarray             # float32 [n_docs] (0 where absent)
    sum_total_term_freq: int
    sum_doc_freq: int
    doc_count: int                        # docs with this field

    _term_index: Optional[Dict[str, int]] = dc_field(default=None, repr=False)

    @property
    def term_index(self) -> Dict[str, int]:
        if self._term_index is None:
            self._term_index = {t: i for i, t in enumerate(self.terms)}
        return self._term_index

    def term_id(self, term: str) -> int:
        return self.term_index.get(term, -1)

    @property
    def num_blocks(self) -> int:
        return self.block_docids.shape[0]

    @property
    def avg_field_length(self) -> float:
        return self.sum_total_term_freq / max(1, self.doc_count)

    def term_blocks(self, term: str) -> Tuple[int, int]:
        """(start, count) block range for a term; (0, 0) if absent."""
        tid = self.term_id(term)
        if tid < 0:
            return 0, 0
        return int(self.term_block_start[tid]), int(self.term_block_count[tid])

    def postings(self, term: str) -> Tuple[np.ndarray, np.ndarray]:
        """(docids, tfs) for one term — host-side scalar access for tests
        and the fetch path; kernels read the block arrays directly."""
        start, count = self.term_blocks(term)
        if count == 0:
            return np.empty(0, np.int32), np.empty(0, np.float32)
        docids = self.block_docids[start : start + count].reshape(-1)
        tfs = self.block_tfs[start : start + count].reshape(-1)
        mask = tfs > 0
        return docids[mask], tfs[mask]


@dataclass
class TokenStreams:
    """Per-doc positional token-id streams for one text field.

    The positional index that Lucene keeps as per-posting position deltas
    is re-homed here as a rectangular array: ``tokens[n_docs, max_len]``
    int32 of term ids (into the field's ``PostingsField.terms``), -1
    padded. Positional queries (match_phrase, slop) become shifted-equality
    array ops over candidate rows instead of postings-iterator
    intersections (ref: Lucene PhraseQuery/ExactPhraseMatcher). Streams
    longer than ``MAX_STREAM_LEN`` are truncated (position index only —
    postings/norms still see the full stream), mirroring
    index.highlight.max_analyzed_offset-style bounded positional work.
    """

    field: str
    tokens: np.ndarray    # int32 [n_docs, max_len], -1 pad
    lengths: np.ndarray   # int32 [n_docs] indexed (possibly truncated) length


MAX_STREAM_LEN = 512


@dataclass
class NumericDocValues:
    field: str
    values: np.ndarray    # float64 [n_docs] (first value if multi)
    missing: np.ndarray   # bool [n_docs]
    # ragged multi-values
    offsets: np.ndarray   # int64 [n_docs + 1]
    all_values: np.ndarray  # float64 [total]

    def get(self, docid: int) -> List[float]:
        return list(self.all_values[self.offsets[docid] : self.offsets[docid + 1]])


@dataclass
class KeywordDocValues:
    """Sorted-set ordinals (analogue of Lucene SortedSetDocValues)."""

    field: str
    terms: List[str]        # sorted unique terms
    ords: np.ndarray        # int32 [n_docs] first ord, -1 = missing
    offsets: np.ndarray     # int64 [n_docs + 1] into all_ords
    all_ords: np.ndarray    # int32 [total]

    def get(self, docid: int) -> List[str]:
        return [self.terms[o] for o in self.all_ords[self.offsets[docid] : self.offsets[docid + 1]]]


@dataclass
class VectorValues:
    field: str
    vectors: np.ndarray     # float32 [n_docs, dims]
    has_value: np.ndarray   # bool [n_docs]
    dims: int
    similarity: str = "cosine"


@dataclass
class StoredFields:
    offsets: np.ndarray     # int64 [n_docs + 1]
    data: bytes
    ids: List[str]

    def source(self, docid: int) -> bytes:
        return self.data[self.offsets[docid] : self.offsets[docid + 1]]


class CompletionValues:
    """Weighted prefix index for the `completion` field type — the
    FST-class replacement for the round-4 linear scan (ref: search/
    suggest/completion/CompletionSuggester.java:41; Lucene builds
    weighted FSTs — NRTSuggester). Equivalent structure here: inputs
    SORTED (prefix → one bisect range) + an implicit segment tree of
    per-node MAX WEIGHT over that order, so top-k extraction pops the
    range's argmax in O(log n) per hit via range splitting — the same
    max-weight-descent that makes FST suggesters sublinear, on arrays
    instead of automata."""

    def __init__(self, field: str, inputs: List[str],
                 weights: np.ndarray, doc_of: np.ndarray,
                 contexts: Optional[List[frozenset]] = None):
        order = sorted(range(len(inputs)), key=lambda i: inputs[i])
        self.field = field
        self.inputs = [inputs[i] for i in order]
        self.weights = np.asarray(weights, np.float64)[order]
        self.doc_of = np.asarray(doc_of, np.int32)[order]
        self.contexts = ([contexts[i] for i in order]
                         if contexts is not None else None)
        n = len(self.inputs)
        # segment tree over weights: tree[1] is the root max; leaves at
        # [size, size + n)
        self._size = 1
        while self._size < max(1, n):
            self._size *= 2
        tree = np.full(2 * self._size, -np.inf, np.float64)
        if n:
            tree[self._size:self._size + n] = self.weights
        for i in range(self._size - 1, 0, -1):
            tree[i] = max(tree[2 * i], tree[2 * i + 1])
        self._tree = tree

    def __len__(self):
        return len(self.inputs)

    def _range_argmax(self, lo: int, hi: int) -> int:
        """Index of the max weight in [lo, hi) — O(log n) tree descent."""
        best_v, best_i = -np.inf, -1
        nodes: List[tuple] = [(1, 0, self._size)]
        while nodes:
            node, nlo, nhi = nodes.pop()
            if nhi <= lo or hi <= nlo or self._tree[node] <= best_v:
                continue
            if nhi - nlo == 1:
                best_v, best_i = self._tree[node], nlo
                continue
            mid = (nlo + nhi) // 2
            # visit the larger child first so pruning bites
            kids = [(2 * node, nlo, mid), (2 * node + 1, mid, nhi)]
            kids.sort(key=lambda k: self._tree[k[0]])
            nodes.extend(kids)
        return best_i

    def top_k(self, prefix: str, k: int,
              context_filter: Optional[frozenset] = None,
              live: Optional[np.ndarray] = None) -> List[int]:
        """Indices of the k highest-weight entries under ``prefix``
        (weight desc, input asc ties), optionally restricted to entries
        carrying EVERY context key in ``context_filter`` and to live
        docs. Heap of ranges split at their argmax: O((k+s) log n)
        where s = entries skipped by the filters."""
        import bisect
        import heapq

        lo = bisect.bisect_left(self.inputs, prefix)
        hi = bisect.bisect_left(self.inputs, prefix + "\U0010FFFF\U0010FFFF")
        if lo >= hi:
            return []
        out: List[int] = []
        first = self._range_argmax(lo, hi)
        heap = [(-self.weights[first], self.inputs[first], first,
                 lo, hi)]
        # the skip budget bounds degenerate context filtering; past it
        # fall back to an exact linear pass over the prefix range
        budget = max(10 * k, 4096)
        while heap and len(out) < k and budget > 0:
            negw, _text, i, rlo, rhi = heapq.heappop(heap)
            ok = True
            if live is not None and not live[self.doc_of[i]]:
                ok = False
            if ok and context_filter:
                ctx = self.contexts[i] if self.contexts else frozenset()
                ok = context_filter <= ctx
            if ok:
                out.append(i)
            else:
                budget -= 1
            for slo, shi in ((rlo, i), (i + 1, rhi)):
                if slo < shi:
                    j = self._range_argmax(slo, shi)
                    if j >= 0:
                        heapq.heappush(
                            heap, (-self.weights[j], self.inputs[j],
                                   j, slo, shi))
        if budget <= 0 and len(out) < k:
            cand = []
            for i in range(lo, hi):
                if live is not None and not live[self.doc_of[i]]:
                    continue
                if context_filter:
                    ctx = (self.contexts[i] if self.contexts
                           else frozenset())
                    if not context_filter <= ctx:
                        continue
                cand.append(i)
            cand.sort(key=lambda i: (-self.weights[i], self.inputs[i]))
            out = cand[:k]
        return out


# ---------------------------------------------------------------------------
# Segment
# ---------------------------------------------------------------------------

class Segment:
    def __init__(self, name: str, n_docs: int,
                 postings: Dict[str, PostingsField],
                 numerics: Dict[str, NumericDocValues],
                 keywords: Dict[str, KeywordDocValues],
                 vectors: Dict[str, VectorValues],
                 stored: StoredFields,
                 live: Optional[np.ndarray] = None,
                 streams: Optional[Dict[str, TokenStreams]] = None,
                 completions: Optional[Dict[str,
                                            CompletionValues]] = None):
        self.name = name
        self.n_docs = n_docs
        self.postings = postings
        self.numerics = numerics
        self.keywords = keywords
        self.vectors = vectors
        self.stored = stored
        self.streams = streams or {}
        self.completions = completions or {}
        self.live = live if live is not None else np.ones(n_docs, dtype=bool)
        self.live_version = 0  # bumps on delete; device caches key on it
        self._id_map: Optional[Dict[str, int]] = None

    @property
    def id_map(self) -> Dict[str, int]:
        if self._id_map is None:
            self._id_map = {i: d for d, i in enumerate(self.stored.ids)}
        return self._id_map

    @property
    def live_doc_count(self) -> int:
        return int(self.live.sum())

    def delete(self, docid: int) -> None:
        """Soft delete — flips the live mask (immutable arrays elsewhere)."""
        self.live = self.live.copy()
        self.live[docid] = False
        self.live_version += 1

    def docid_for(self, doc_id: str) -> int:
        d = self.id_map.get(doc_id, -1)
        if d >= 0 and not self.live[d]:
            return -1
        return d

    def ram_bytes(self) -> int:
        total = self.live.nbytes + self.stored.offsets.nbytes + len(self.stored.data)
        for pf in self.postings.values():
            total += (pf.block_docids.nbytes + pf.block_tfs.nbytes +
                      pf.block_max_tf.nbytes + pf.block_min_len.nbytes +
                      pf.field_lengths.nbytes + pf.doc_freq.nbytes +
                      pf.term_block_start.nbytes + pf.term_block_count.nbytes)
        for nv in self.numerics.values():
            total += nv.values.nbytes + nv.all_values.nbytes
        for vv in self.vectors.values():
            total += vv.vectors.nbytes
        return total

    # ------------------------------------------------------------------ I/O
    @staticmethod
    def _encode_strings(strings: List[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Arbitrary strings -> (utf-8 blob, offsets); newline-safe."""
        encoded = [s.encode("utf-8") for s in strings]
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = np.frombuffer(b"".join(encoded), dtype=np.uint8)
        return blob, offsets

    @staticmethod
    def _decode_strings(blob: np.ndarray, offsets: np.ndarray) -> List[str]:
        raw = blob.tobytes()
        return [raw[offsets[i]:offsets[i + 1]].decode("utf-8")
                for i in range(len(offsets) - 1)]

    def save(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {"live": self.live}
        meta: Dict[str, Any] = {
            "format_version": SEGMENT_FORMAT_VERSION,
            "name": self.name, "n_docs": self.n_docs,
            "postings": {}, "numerics": [], "keywords": {}, "vectors": {},
        }
        for f, pf in self.postings.items():
            key = f"p~{f}"
            arrays[f"{key}~doc_freq"] = pf.doc_freq
            arrays[f"{key}~ttf"] = pf.total_term_freq
            arrays[f"{key}~tbs"] = pf.term_block_start
            arrays[f"{key}~tbc"] = pf.term_block_count
            arrays[f"{key}~bd"] = pf.block_docids
            arrays[f"{key}~bt"] = pf.block_tfs
            arrays[f"{key}~bmt"] = pf.block_max_tf
            arrays[f"{key}~bml"] = pf.block_min_len
            arrays[f"{key}~fl"] = pf.field_lengths
            arrays[f"{key}~terms"], arrays[f"{key}~terms_off"] = \
                self._encode_strings(pf.terms)
            meta["postings"][f] = {
                "sum_total_term_freq": pf.sum_total_term_freq,
                "sum_doc_freq": pf.sum_doc_freq,
                "doc_count": pf.doc_count,
            }
        for f, nv in self.numerics.items():
            key = f"n~{f}"
            arrays[f"{key}~v"] = nv.values
            arrays[f"{key}~m"] = nv.missing
            arrays[f"{key}~o"] = nv.offsets
            arrays[f"{key}~av"] = nv.all_values
            meta["numerics"].append(f)
        for f, kv in self.keywords.items():
            key = f"k~{f}"
            arrays[f"{key}~ords"] = kv.ords
            arrays[f"{key}~o"] = kv.offsets
            arrays[f"{key}~ao"] = kv.all_ords
            arrays[f"{key}~terms"], arrays[f"{key}~terms_off"] = \
                self._encode_strings(kv.terms)
            meta["keywords"][f] = {}
        for f, vv in self.vectors.items():
            key = f"v~{f}"
            arrays[f"{key}~vec"] = vv.vectors
            arrays[f"{key}~has"] = vv.has_value
            meta["vectors"][f] = {"dims": vv.dims, "similarity": vv.similarity}
        meta["streams"] = []
        for f, ts in self.streams.items():
            key = f"s~{f}"
            arrays[f"{key}~tok"] = ts.tokens
            arrays[f"{key}~len"] = ts.lengths
            meta["streams"].append(f)
        meta["completions"] = {}
        for f, cv in self.completions.items():
            key = f"c~{f}"
            arrays[f"{key}~in"], arrays[f"{key}~in_off"] = \
                self._encode_strings(cv.inputs)
            arrays[f"{key}~w"] = cv.weights
            arrays[f"{key}~d"] = cv.doc_of
            if cv.contexts is not None:
                ctx_strs = ["\x1f".join(sorted(c)) for c in cv.contexts]
                arrays[f"{key}~ctx"], arrays[f"{key}~ctx_off"] = \
                    self._encode_strings(ctx_strs)
            meta["completions"][f] = {
                "has_contexts": cv.contexts is not None}
        arrays["stored~offsets"] = self.stored.offsets
        arrays["stored~ids"], arrays["stored~ids_off"] = \
            self._encode_strings(self.stored.ids)
        np.savez(os.path.join(directory, "arrays.npz"), **arrays)
        with open(os.path.join(directory, "stored.bin"), "wb") as fh:
            fh.write(self.stored.data)
        with open(os.path.join(directory, "meta.json"), "w") as fh:
            json.dump(meta, fh)

    @classmethod
    def load(cls, directory: str) -> "Segment":
        with open(os.path.join(directory, "meta.json")) as fh:
            meta = json.load(fh)
        fmt = int(meta.get("format_version", 1))
        if fmt > SEGMENT_FORMAT_VERSION:
            raise IOError(
                f"segment [{directory}] was written by a NEWER build "
                f"(format {fmt} > supported {SEGMENT_FORMAT_VERSION}); "
                f"downgrades are not supported (ref: Lucene version "
                f"guards on SegmentInfos)")
        with open(os.path.join(directory, "stored.bin"), "rb") as fh:
            data = fh.read()
        z = np.load(os.path.join(directory, "arrays.npz"))

        postings = {}
        for f, m in meta["postings"].items():
            key = f"p~{f}"
            postings[f] = PostingsField(
                field=f,
                terms=cls._decode_strings(z[f"{key}~terms"], z[f"{key}~terms_off"]),
                doc_freq=z[f"{key}~doc_freq"], total_term_freq=z[f"{key}~ttf"],
                term_block_start=z[f"{key}~tbs"], term_block_count=z[f"{key}~tbc"],
                block_docids=z[f"{key}~bd"], block_tfs=z[f"{key}~bt"],
                block_max_tf=z[f"{key}~bmt"], block_min_len=z[f"{key}~bml"],
                field_lengths=z[f"{key}~fl"],
                sum_total_term_freq=m["sum_total_term_freq"],
                sum_doc_freq=m["sum_doc_freq"], doc_count=m["doc_count"])
        numerics = {}
        for f in meta["numerics"]:
            key = f"n~{f}"
            numerics[f] = NumericDocValues(
                field=f, values=z[f"{key}~v"], missing=z[f"{key}~m"],
                offsets=z[f"{key}~o"], all_values=z[f"{key}~av"])
        keywords = {}
        for f in meta["keywords"]:
            key = f"k~{f}"
            keywords[f] = KeywordDocValues(
                field=f,
                terms=cls._decode_strings(z[f"{key}~terms"], z[f"{key}~terms_off"]),
                ords=z[f"{key}~ords"], offsets=z[f"{key}~o"],
                all_ords=z[f"{key}~ao"])
        vectors = {}
        for f, m in meta["vectors"].items():
            key = f"v~{f}"
            vectors[f] = VectorValues(
                field=f, vectors=z[f"{key}~vec"], has_value=z[f"{key}~has"],
                dims=m["dims"], similarity=m["similarity"])
        streams = {}
        for f in meta.get("streams", []):
            key = f"s~{f}"
            streams[f] = TokenStreams(f, z[f"{key}~tok"], z[f"{key}~len"])
        completions = {}
        for f, m in meta.get("completions", {}).items():
            key = f"c~{f}"
            inputs = cls._decode_strings(z[f"{key}~in"],
                                         z[f"{key}~in_off"])
            ctxs = None
            if m.get("has_contexts"):
                ctx_strs = cls._decode_strings(z[f"{key}~ctx"],
                                               z[f"{key}~ctx_off"])
                ctxs = [frozenset(s.split("\x1f")) if s else frozenset()
                        for s in ctx_strs]
            completions[f] = CompletionValues(
                f, inputs, z[f"{key}~w"], z[f"{key}~d"], ctxs)
        stored = StoredFields(
            offsets=z["stored~offsets"], data=data,
            ids=cls._decode_strings(z["stored~ids"], z["stored~ids_off"]))
        return cls(meta["name"], meta["n_docs"], postings, numerics, keywords,
                   vectors, stored, live=z["live"].astype(bool),
                   streams=streams, completions=completions)


# ---------------------------------------------------------------------------
# Segment writer
# ---------------------------------------------------------------------------

class SegmentWriter:
    """Accumulates parsed documents, then builds an immutable Segment
    (the analogue of Lucene's IndexingChain + flush)."""

    def __init__(self):
        self._docs: List[Any] = []  # ParsedDocument

    def add(self, parsed) -> int:
        self._docs.append(parsed)
        return len(self._docs) - 1

    def __len__(self):
        return len(self._docs)

    @property
    def docs(self):
        return self._docs

    def build(self, name: str) -> Segment:
        docs = self._docs
        n = len(docs)

        # ---- postings: text fields (tf = within-doc term count) and
        #      keyword fields (tf = 1, also feeds ordinals)
        field_term_docs: Dict[str, Dict[str, List[Tuple[int, float]]]] = {}
        field_lengths: Dict[str, np.ndarray] = {}
        for docid, d in enumerate(docs):
            for f, toks in d.text_tokens.items():
                per = field_term_docs.setdefault(f, {})
                counts: Dict[str, int] = {}
                for t in toks:
                    counts[t.term] = counts.get(t.term, 0) + 1
                for term, tf in counts.items():
                    per.setdefault(term, []).append((docid, float(tf)))
                field_lengths.setdefault(f, np.zeros(n, np.float32))[docid] = len(toks)
            for f, terms in d.keyword_terms.items():
                per = field_term_docs.setdefault(f, {})
                for term in set(terms):
                    per.setdefault(term, []).append((docid, 1.0))
                field_lengths.setdefault(f, np.zeros(n, np.float32))[docid] = len(terms)

        postings = {
            f: _build_postings_field(f, term_docs, field_lengths[f], n)
            for f, term_docs in field_term_docs.items()
        }

        # ---- positional token streams (text fields only). Tokens land at
        # their Token.position slot — position gaps from e.g. StopFilter
        # stay as -1 holes, so phrase adjacency respects increments exactly
        # as Lucene position deltas do.
        streams: Dict[str, TokenStreams] = {}
        text_fields = {f for d in docs for f in d.text_tokens}
        for f in text_fields:
            tindex = postings[f].term_index
            max_len = min(
                MAX_STREAM_LEN,
                max((ts[-1].position + 1 for d in docs
                     if (ts := d.text_tokens.get(f))), default=0))
            toks = np.full((n, max_len), -1, np.int32)
            lengths = np.zeros(n, np.int32)
            for docid, d in enumerate(docs):
                ts = d.text_tokens.get(f)
                if not ts:
                    continue
                L = 0
                for t in ts:
                    if t.position >= max_len:
                        break
                    # first write wins: same-position synonym tokens
                    # (annotated_text annotation values) must not
                    # evict the anchor text token from the stream —
                    # they stay phrase-invisible but postings-searchable
                    if toks[docid, t.position] < 0:
                        toks[docid, t.position] = tindex[t.term]
                    L = max(L, t.position + 1)
                lengths[docid] = L
            streams[f] = TokenStreams(f, toks, lengths)

        # ---- numeric doc values
        numerics = {}
        num_fields = {f for d in docs for f in d.numeric_values}
        for f in num_fields:
            values = np.full(n, np.nan, np.float64)
            missing = np.ones(n, bool)
            offsets = np.zeros(n + 1, np.int64)
            all_vals: List[float] = []
            for docid, d in enumerate(docs):
                vs = d.numeric_values.get(f, [])
                if vs:
                    values[docid] = vs[0]
                    missing[docid] = False
                    all_vals.extend(sorted(vs))
                offsets[docid + 1] = len(all_vals)
            numerics[f] = NumericDocValues(f, values, missing, offsets,
                                           np.asarray(all_vals, np.float64))

        # ---- keyword ordinals
        keywords = {}
        kw_fields = {f for d in docs for f in d.keyword_terms}
        for f in kw_fields:
            uniq = sorted({t for d in docs for t in d.keyword_terms.get(f, [])})
            tindex = {t: i for i, t in enumerate(uniq)}
            ords = np.full(n, -1, np.int32)
            offsets = np.zeros(n + 1, np.int64)
            all_ords: List[int] = []
            for docid, d in enumerate(docs):
                terms = sorted(set(d.keyword_terms.get(f, [])))
                if terms:
                    ords[docid] = tindex[terms[0]]
                    all_ords.extend(tindex[t] for t in terms)
                offsets[docid + 1] = len(all_ords)
            keywords[f] = KeywordDocValues(f, uniq, ords, offsets,
                                           np.asarray(all_ords, np.int32))

        # ---- vectors
        vectors = {}
        vec_fields = {f for d in docs for f in d.vectors}
        for f in vec_fields:
            dims = next(d.vectors[f].shape[0] for d in docs if f in d.vectors)
            sim = next((d.vector_similarity.get(f, "cosine") for d in docs
                        if f in d.vectors), "cosine")
            arr = np.zeros((n, dims), np.float32)
            has = np.zeros(n, bool)
            for docid, d in enumerate(docs):
                v = d.vectors.get(f)
                if v is not None:
                    arr[docid] = v
                    has[docid] = True
            vectors[f] = VectorValues(f, arr, has, dims, sim)

        # ---- stored fields
        offsets = np.zeros(n + 1, np.int64)
        chunks = []
        ids = []
        total = 0
        for docid, d in enumerate(docs):
            chunks.append(d.source)
            total += len(d.source)
            offsets[docid + 1] = total
            ids.append(d.doc_id)
        stored = StoredFields(offsets, b"".join(chunks), ids)

        # ---- completion fields: weighted prefix indexes
        completions = {}
        comp_fields = {f for d in docs
                       for f in getattr(d, "completion_entries", {})}
        for f in comp_fields:
            inputs: List[str] = []
            ws: List[float] = []
            doc_of: List[int] = []
            ctxs: List[frozenset] = []
            for docid, d in enumerate(docs):
                for inp, w, cx in getattr(
                        d, "completion_entries", {}).get(f, []):
                    inputs.append(inp)
                    ws.append(float(w))
                    doc_of.append(docid)
                    ctxs.append(cx)
            completions[f] = CompletionValues(
                f, inputs, np.asarray(ws), np.asarray(doc_of),
                ctxs if any(ctxs) else None)

        return Segment(name, n, postings, numerics, keywords, vectors, stored,
                       streams=streams, completions=completions)


def _build_postings_field(field: str,
                          term_docs: Dict[str, Any],
                          field_lengths: np.ndarray, n_docs: int) -> PostingsField:
    """term_docs values are either a list of (docid, tf) tuples (writer path)
    or a list of (docids_array, tfs_array) chunks (merge path) — both
    docid-ascending."""
    terms = sorted(term_docs)
    num_terms = len(terms)
    doc_freq = np.zeros(num_terms, np.int32)
    ttf = np.zeros(num_terms, np.int64)
    tbs = np.zeros(num_terms, np.int32)
    tbc = np.zeros(num_terms, np.int32)

    blocks_d: List[np.ndarray] = []
    blocks_t: List[np.ndarray] = []
    next_block = 0
    for tid, term in enumerate(terms):
        plist = term_docs[term]
        if plist and isinstance(plist[0], tuple) and np.isscalar(plist[0][0]):
            docids = np.asarray([p[0] for p in plist], np.int32)
            tfs = np.asarray([p[1] for p in plist], np.float32)
        else:
            docids = np.concatenate([c[0] for c in plist]).astype(np.int32)
            tfs = np.concatenate([c[1] for c in plist]).astype(np.float32)
        doc_freq[tid] = len(docids)
        ttf[tid] = int(tfs.sum())
        nb = (len(docids) + BLOCK_SIZE - 1) // BLOCK_SIZE
        tbs[tid] = next_block
        tbc[tid] = nb
        next_block += nb
        pad = nb * BLOCK_SIZE - len(docids)
        if pad:
            # tf=0 padding scores exactly 0; docid 0 is a harmless target
            docids = np.concatenate([docids, np.zeros(pad, np.int32)])
            tfs = np.concatenate([tfs, np.zeros(pad, np.float32)])
        blocks_d.append(docids.reshape(nb, BLOCK_SIZE))
        blocks_t.append(tfs.reshape(nb, BLOCK_SIZE))

    if blocks_d:
        block_docids = np.concatenate(blocks_d, axis=0)
        block_tfs = np.concatenate(blocks_t, axis=0)
    else:
        block_docids = np.zeros((0, BLOCK_SIZE), np.int32)
        block_tfs = np.zeros((0, BLOCK_SIZE), np.float32)

    # block-max metadata: tf upper bound and doc-length lower bound
    block_max_tf = block_tfs.max(axis=1) if len(block_tfs) else np.zeros(0, np.float32)
    if len(block_docids):
        lens = field_lengths[block_docids]          # [nb, B]
        lens = np.where(block_tfs > 0, lens, np.inf)
        block_min_len = lens.min(axis=1).astype(np.float32)
        block_min_len[~np.isfinite(block_min_len)] = 0.0
    else:
        block_min_len = np.zeros(0, np.float32)

    return PostingsField(
        field=field, terms=terms, doc_freq=doc_freq, total_term_freq=ttf,
        term_block_start=tbs, term_block_count=tbc,
        block_docids=block_docids, block_tfs=block_tfs,
        block_max_tf=block_max_tf.astype(np.float32),
        block_min_len=block_min_len,
        field_lengths=field_lengths,
        sum_total_term_freq=int(ttf.sum()),
        sum_doc_freq=int(doc_freq.sum()),
        doc_count=int((field_lengths > 0).sum()))


# ---------------------------------------------------------------------------
# Merge (the analogue of Lucene segment merging; runs on host CPU)
# ---------------------------------------------------------------------------

def merge_segments(name: str, segments: List[Segment]) -> Segment:
    """Merge segments, dropping deleted docs and remapping docids.

    ref: Lucene SegmentMerger / ElasticsearchConcurrentMergeScheduler —
    here a host-side columnar merge: per-segment docid -> new docid maps,
    then concatenation of per-term postings in segment order (docids stay
    ascending because new ids are assigned in segment order).
    """
    # docid remap: old (seg, docid) -> new docid, skipping deletes
    maps: List[np.ndarray] = []
    new_n = 0
    for seg in segments:
        m = np.full(seg.n_docs, -1, np.int64)
        live_ids = np.nonzero(seg.live)[0]
        m[live_ids] = np.arange(new_n, new_n + len(live_ids))
        new_n += len(live_ids)
        maps.append(m)

    # ---- postings
    all_fields = sorted({f for s in segments for f in s.postings})
    postings: Dict[str, PostingsField] = {}
    for f in all_fields:
        # term -> list of (docids_array, tfs_array) chunks, appended in
        # segment order so merged docids stay ascending
        term_docs: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        field_lengths = np.zeros(new_n, np.float32)
        for seg, m in zip(segments, maps):
            pf = seg.postings.get(f)
            if pf is None:
                continue
            live = seg.live
            new_ids = m[np.arange(seg.n_docs)]
            keep = new_ids >= 0
            field_lengths[new_ids[keep]] = pf.field_lengths[keep]
            for tid, term in enumerate(pf.terms):
                start, count = int(pf.term_block_start[tid]), int(pf.term_block_count[tid])
                docids = pf.block_docids[start : start + count].reshape(-1)
                tfs = pf.block_tfs[start : start + count].reshape(-1)
                mask = (tfs > 0) & live[docids]
                if not mask.any():
                    continue
                term_docs.setdefault(term, []).append(
                    (m[docids[mask]].astype(np.int64), tfs[mask]))
        postings[f] = _build_postings_field(f, term_docs, field_lengths, new_n)

    # ---- numerics
    numerics: Dict[str, NumericDocValues] = {}
    for f in sorted({f for s in segments for f in s.numerics}):
        values = np.full(new_n, np.nan, np.float64)
        missing = np.ones(new_n, bool)
        offsets = np.zeros(new_n + 1, np.int64)
        all_vals: List[np.ndarray] = []
        total = 0
        counts = np.zeros(new_n, np.int64)
        per_doc: Dict[int, np.ndarray] = {}
        for seg, m in zip(segments, maps):
            nv = seg.numerics.get(f)
            if nv is None:
                continue
            for old in np.nonzero(seg.live)[0]:
                new = int(m[old])
                vs = nv.all_values[nv.offsets[old] : nv.offsets[old + 1]]
                if len(vs):
                    values[new] = nv.values[old]
                    missing[new] = False
                    per_doc[new] = vs
        for d in range(new_n):
            vs = per_doc.get(d)
            if vs is not None:
                all_vals.append(vs)
                total += len(vs)
            offsets[d + 1] = total
        numerics[f] = NumericDocValues(
            f, values, missing, offsets,
            np.concatenate(all_vals) if all_vals else np.zeros(0, np.float64))

    # ---- keywords
    keywords: Dict[str, KeywordDocValues] = {}
    for f in sorted({f for s in segments for f in s.keywords}):
        per_doc_terms: Dict[int, List[str]] = {}
        for seg, m in zip(segments, maps):
            kv = seg.keywords.get(f)
            if kv is None:
                continue
            for old in np.nonzero(seg.live)[0]:
                ts = kv.get(int(old))
                if ts:
                    per_doc_terms[int(m[old])] = ts
        uniq = sorted({t for ts in per_doc_terms.values() for t in ts})
        tindex = {t: i for i, t in enumerate(uniq)}
        ords = np.full(new_n, -1, np.int32)
        offsets = np.zeros(new_n + 1, np.int64)
        all_ords: List[int] = []
        for d in range(new_n):
            ts = per_doc_terms.get(d, [])
            if ts:
                ords[d] = tindex[ts[0]]
                all_ords.extend(tindex[t] for t in ts)
            offsets[d + 1] = len(all_ords)
        keywords[f] = KeywordDocValues(f, uniq, ords, offsets,
                                       np.asarray(all_ords, np.int32))

    # ---- token streams (remap old term ids -> merged term ids)
    streams: Dict[str, TokenStreams] = {}
    for f in sorted({f for s in segments for f in s.streams}):
        pf_new = postings.get(f)
        if pf_new is None:
            continue
        max_len = max(s.streams[f].tokens.shape[1]
                      for s in segments if f in s.streams)
        toks = np.full((new_n, max_len), -1, np.int32)
        lengths = np.zeros(new_n, np.int32)
        new_index = pf_new.term_index
        for seg, m in zip(segments, maps):
            ts = seg.streams.get(f)
            if ts is None:
                continue
            old_terms = seg.postings[f].terms
            # old term id -> new term id (deleted-only terms map to -1)
            remap = np.fromiter(
                (new_index.get(t, -1) for t in old_terms),
                np.int32, count=len(old_terms))
            remap = np.concatenate([remap, np.asarray([-1], np.int32)])  # -1 pad slot
            live_ids = np.nonzero(seg.live)[0]
            L = ts.tokens.shape[1]
            toks[m[live_ids], :L] = remap[ts.tokens[live_ids]]
            lengths[m[live_ids]] = ts.lengths[live_ids]
        streams[f] = TokenStreams(f, toks, lengths)

    # ---- vectors
    vectors: Dict[str, VectorValues] = {}
    for f in sorted({f for s in segments for f in s.vectors}):
        dims = next(s.vectors[f].dims for s in segments if f in s.vectors)
        sim = next(s.vectors[f].similarity for s in segments if f in s.vectors)
        arr = np.zeros((new_n, dims), np.float32)
        has = np.zeros(new_n, bool)
        for seg, m in zip(segments, maps):
            vv = seg.vectors.get(f)
            if vv is None:
                continue
            keep = seg.live
            arr[m[keep]] = vv.vectors[keep]
            has[m[keep]] = vv.has_value[keep]
        vectors[f] = VectorValues(f, arr, has, dims, sim)

    # ---- stored
    offsets = np.zeros(new_n + 1, np.int64)
    chunks: List[bytes] = []
    ids: List[str] = []
    total = 0
    for seg, m in zip(segments, maps):
        for old in np.nonzero(seg.live)[0]:
            src = seg.stored.source(int(old))
            chunks.append(src)
            total += len(src)
            offsets[int(m[old]) + 1] = total
            ids.append(seg.stored.ids[int(old)])
    stored = StoredFields(offsets, b"".join(chunks), ids)

    # ---- completions: rebuild the prefix index over surviving docs
    completions = {}
    comp_fields = {f for s in segments for f in s.completions}
    for f in comp_fields:
        inputs: List[str] = []
        ws: List[float] = []
        doc_of: List[int] = []
        ctxs: List[frozenset] = []
        any_ctx = False
        for seg, m in zip(segments, maps):
            cv = seg.completions.get(f)
            if cv is None:
                continue
            for i in range(len(cv)):
                old = int(cv.doc_of[i])
                if not seg.live[old]:
                    continue
                inputs.append(cv.inputs[i])
                ws.append(float(cv.weights[i]))
                doc_of.append(int(m[old]))
                cx = (cv.contexts[i] if cv.contexts is not None
                      else frozenset())
                any_ctx = any_ctx or bool(cx)
                ctxs.append(cx)
        completions[f] = CompletionValues(
            f, inputs, np.asarray(ws), np.asarray(doc_of),
            ctxs if any_ctx else None)

    return Segment(name, new_n, postings, numerics, keywords, vectors, stored,
                   streams=streams, completions=completions)
