"""Indexing pressure: in-flight-byte admission control for the write path.

Mirrors the reference's ``IndexingPressure`` (ref: index/IndexingPressure
.java, new in 8.0): every bulk charges its payload bytes at each stage it
passes through — coordinating (the node that fans out), primary (the node
executing the shard bulk), replica (each in-sync copy applying pre-seqno'd
ops) — and releases them when that stage completes. Past the configured
limit the operation is rejected with a retryable 429
(``EsRejectedExecutionException``) BEFORE any shard work happens, so an
overloaded node sheds load instead of buffering itself to death.

Semantics preserved from the reference:

- coordinating + primary share one budget (``limit``); a replica gets
  1.5x headroom (``replica_limit``) so replication — which frees primary
  bytes elsewhere — is shed LAST (rejecting replica writes can only make
  the cluster sicker).
- rejection counters are per stage and cumulative; current bytes return
  to zero when every in-flight operation has released (the
  release-on-completion invariant pinned in tests/test_backpressure.py).
- the stats shape follows ``GET /_nodes/stats``'s ``indexing_pressure``
  section.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import EsRejectedExecutionException

# default in-flight-bytes budget (the reference defaults to 10% of heap;
# a fixed, generous default keeps the unconfigured path unthrottled)
DEFAULT_LIMIT_BYTES = 64 * 1024 * 1024
LIMIT_SETTING = "indexing_pressure.memory.limit"

COORDINATING = "coordinating"
PRIMARY = "primary"
REPLICA = "replica"


def operation_size_bytes(items) -> int:
    """Wire-size estimate of a bulk payload (the analogue of the
    reference's ``ramBytesUsed`` per DocWriteRequest) — delegates to
    the shared sizer in utils/breaker.py so indexing-pressure and
    transport-breaker accounting can never drift."""
    from elasticsearch_tpu.utils.breaker import payload_size_bytes
    return payload_size_bytes(items)


class IndexingPressure:
    """Per-node in-flight indexing byte accounting (threadsafe)."""

    @classmethod
    def from_settings(cls, settings_get, metrics=None) -> "IndexingPressure":
        """Build from node settings (`indexing_pressure.memory.limit`);
        an explicit 0 is honored, not replaced by the default."""
        from elasticsearch_tpu.common.settings import parse_byte_size
        raw = settings_get(LIMIT_SETTING)
        limit = (parse_byte_size(raw, LIMIT_SETTING)
                 if raw is not None else DEFAULT_LIMIT_BYTES)
        return cls(limit, metrics=metrics)

    def __init__(self, limit_bytes: int = DEFAULT_LIMIT_BYTES,
                 metrics=None):
        self.limit = int(limit_bytes)
        # replica ops get 1.5x headroom (ref: IndexingPressure — replica
        # rejections amplify cluster load, shed them last)
        self._lock = threading.Lock()
        self._current = {COORDINATING: 0, PRIMARY: 0, REPLICA: 0}
        self._total = {COORDINATING: 0, PRIMARY: 0, REPLICA: 0}
        self._rejections = {COORDINATING: 0, PRIMARY: 0, REPLICA: 0}
        self._peak_all = 0
        # telemetry sink (MetricsRegistry or None): one branch per event
        self.metrics = metrics
        # optional TenantAccounting sink: payload bytes charged to the
        # ambient tenant at the COORDINATING stage only (primary/replica
        # marks are the same payload fanning out — charging them too
        # would double-count), rejections at every stage
        self.tenants = None
        # optional WorkloadAccounting sink: same charge policy keyed by
        # the ambient workload class
        self.workloads = None

    @property
    def replica_limit(self) -> int:
        return int(self.limit * 1.5) if self.limit >= 0 else -1

    # ------------------------------------------------------------- marks

    def mark_coordinating_operation_started(
            self, n_bytes: int, label: str = "bulk"
    ) -> Callable[[], None]:
        return self._mark(COORDINATING, n_bytes, label)

    def mark_primary_operation_started(
            self, n_bytes: int, label: str = "bulk[s][p]"
    ) -> Callable[[], None]:
        return self._mark(PRIMARY, n_bytes, label)

    def mark_replica_operation_started(
            self, n_bytes: int, label: str = "bulk[s][r]"
    ) -> Callable[[], None]:
        return self._mark(REPLICA, n_bytes, label)

    def _mark(self, stage: str, n_bytes: int,
              label: str) -> Callable[[], None]:
        n_bytes = int(n_bytes)
        tenant = None
        wclass = None
        if self.tenants is not None or self.workloads is not None:
            from elasticsearch_tpu.telemetry import context as _telectx
            tenant = _telectx.current_tenant()
            wclass = _telectx.current_workload_class()
        with self._lock:
            # coordinating + primary share the base budget; replica ops
            # get the 1.5x headroom. All stages' bytes count toward the
            # admission total — they are real memory either way.
            limit = self.replica_limit if stage == REPLICA else self.limit
            would = sum(self._current.values()) + n_bytes
            if 0 <= limit < would:
                self._rejections[stage] += 1
                if self.metrics is not None:
                    self.metrics.inc("indexing_pressure.rejections",
                                     stage=stage)
                if self.tenants is not None:
                    self.tenants.record_rejection(tenant, stage)
                if self.workloads is not None:
                    self.workloads.record_rejection(wclass, stage)
                raise EsRejectedExecutionException(
                    f"rejecting operation [{label}] at {stage} stage: "
                    f"in-flight indexing bytes [{would}] would exceed "
                    f"the limit of [{limit}] "
                    f"({LIMIT_SETTING}={self.limit})",
                    bytes_wanted=would, bytes_limit=limit)
            self._current[stage] += n_bytes
            self._total[stage] += n_bytes
            self._peak_all = max(self._peak_all,
                                 sum(self._current.values()))
        if self.tenants is not None and stage == COORDINATING:
            self.tenants.record_indexing(tenant, n_bytes)
        if self.workloads is not None and stage == COORDINATING:
            self.workloads.record_indexing(wclass, n_bytes)
        released = {"done": False}

        def release() -> None:
            if released["done"]:
                return
            released["done"] = True
            with self._lock:
                self._current[stage] -= n_bytes

        return release

    # ------------------------------------------------------------- stats

    def current_bytes(self, stage: Optional[str] = None) -> int:
        with self._lock:
            if stage is None:
                return sum(self._current.values())
            return self._current[stage]

    def rejections(self, stage: str) -> int:
        with self._lock:
            return self._rejections[stage]

    @property
    def peak_all_bytes(self) -> int:
        with self._lock:
            return self._peak_all

    def stats(self) -> Dict[str, Any]:
        """The ``indexing_pressure`` section of ``GET /_nodes/stats``
        (ref: IndexingPressureStats)."""
        with self._lock:
            cur = dict(self._current)
            tot = dict(self._total)
            rej = dict(self._rejections)
            peak = self._peak_all
        return {"memory": {
            "current": {
                "combined_coordinating_and_primary_in_bytes":
                    cur[COORDINATING] + cur[PRIMARY],
                "coordinating_in_bytes": cur[COORDINATING],
                "primary_in_bytes": cur[PRIMARY],
                "replica_in_bytes": cur[REPLICA],
                "all_in_bytes": sum(cur.values()),
            },
            "total": {
                "combined_coordinating_and_primary_in_bytes":
                    tot[COORDINATING] + tot[PRIMARY],
                "coordinating_in_bytes": tot[COORDINATING],
                "primary_in_bytes": tot[PRIMARY],
                "replica_in_bytes": tot[REPLICA],
                "all_in_bytes": sum(tot.values()),
                "peak_all_in_bytes": peak,
                "coordinating_rejections": rej[COORDINATING],
                "primary_rejections": rej[PRIMARY],
                "replica_rejections": rej[REPLICA],
            },
            "limit_in_bytes": self.limit,
        }}
