"""Mapping: schema definition and document parsing.

Mirrors the reference's mapper layer (ref: index/mapper/MapperService.java,
DocumentParser.java:46,58, MappedFieldType.java): a MapperService owns the
DocumentMapper for an index; DocumentParser turns a JSON document into typed
per-field values (the analogue of LuceneDocument) including dynamic-mapping
detection; ~15 core field types including dense_vector (ref: x-pack vectors
DenseVectorFieldMapper.java:44-47 — ≤2048 dims).

TPU orientation: parse output is columnar-friendly — text fields yield token
lists destined for postings blocks, numeric/date/bool fields yield doc
values destined for columnar arrays, dense_vector fields yield fixed-dim
float arrays destined for the [n_docs, dim] HBM slab.
"""

from __future__ import annotations

import datetime as _dt
import json
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.analysis import AnalysisRegistry, Token
from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
    StrictDynamicMappingException,
)
from elasticsearch_tpu.common.settings import Settings


# ---------------------------------------------------------------------------
# Field types
# ---------------------------------------------------------------------------

class MappedFieldType:
    """A field's type: how values parse, index, and store as doc values."""

    type_name = "?"
    # which columnar representation this field feeds on device
    #   "postings"  — inverted text terms -> postings blocks
    #   "term"      — untokenized keyword terms -> postings blocks + ordinals
    #   "numeric"   — float64 column
    #   "vector"    — [dim] float slab row
    #   "none"      — not indexed
    docvalue_kind = "none"

    def __init__(self, name: str, params: Optional[Dict[str, Any]] = None):
        self.name = name
        self.params = params or {}
        self.index = self.params.get("index", True)
        self.store = self.params.get("store", False)
        self.has_doc_values = self.params.get("doc_values", True)

    def parse(self, value: Any) -> Any:
        """JSON value -> internal typed value."""
        raise NotImplementedError

    def to_mapping(self) -> Dict[str, Any]:
        out = {"type": self.type_name}
        out.update({k: v for k, v in self.params.items()})
        return out


class TextFieldType(MappedFieldType):
    type_name = "text"
    docvalue_kind = "postings"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.analyzer_name = self.params.get("analyzer", "standard")
        self.search_analyzer_name = self.params.get("search_analyzer", self.analyzer_name)

    def parse(self, value):
        return str(value)


class KeywordFieldType(MappedFieldType):
    type_name = "keyword"
    docvalue_kind = "term"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.ignore_above = self.params.get("ignore_above", 2 ** 31 - 1)

    def parse(self, value):
        s = str(value)
        if len(s) > self.ignore_above:
            return None
        return s


class _NumericFieldType(MappedFieldType):
    docvalue_kind = "numeric"
    _min = None
    _max = None
    _cast = float

    def parse(self, value):
        try:
            v = self._cast(value)
        except (ValueError, TypeError):
            raise MapperParsingException(
                f"failed to parse field [{self.name}] of type [{self.type_name}]: "
                f"For input string: \"{value}\"")
        if self._min is not None and (v < self._min or v > self._max):
            raise MapperParsingException(
                f"Value [{value}] is out of range for field [{self.name}] "
                f"of type [{self.type_name}]")
        return v


class LongFieldType(_NumericFieldType):
    type_name = "long"
    _cast = int
    _min, _max = -(2 ** 63), 2 ** 63 - 1


class IntegerFieldType(_NumericFieldType):
    type_name = "integer"
    _cast = int
    _min, _max = -(2 ** 31), 2 ** 31 - 1


class ShortFieldType(_NumericFieldType):
    type_name = "short"
    _cast = int
    _min, _max = -(2 ** 15), 2 ** 15 - 1


class ByteFieldType(_NumericFieldType):
    type_name = "byte"
    _cast = int
    _min, _max = -(2 ** 7), 2 ** 7 - 1


class DoubleFieldType(_NumericFieldType):
    type_name = "double"


class FloatFieldType(_NumericFieldType):
    type_name = "float"


class HalfFloatFieldType(_NumericFieldType):
    type_name = "half_float"

    def parse(self, value):
        return float(np.float16(super().parse(value)))


class BooleanFieldType(MappedFieldType):
    type_name = "boolean"
    docvalue_kind = "numeric"

    def parse(self, value):
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if value in ("true", "True"):
            return 1.0
        if value in ("false", "False", ""):
            return 0.0
        raise MapperParsingException(
            f"failed to parse field [{self.name}] of type [boolean]: [{value}]")


_DATE_FORMATS = [
    "%Y-%m-%dT%H:%M:%S.%f%z", "%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S.%f",
    "%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d", "%Y/%m/%d",
]


class DateFieldType(MappedFieldType):
    """Dates stored as epoch millis float64 (ref: DateFieldMapper's
    `strict_date_optional_time||epoch_millis` default format)."""

    type_name = "date"
    docvalue_kind = "numeric"

    def parse(self, value):
        if isinstance(value, (int, float)):
            return float(value)
        s = str(value)
        if re.fullmatch(r"-?\d+", s):
            return float(int(s))
        for fmt in _DATE_FORMATS:
            try:
                dt = _dt.datetime.strptime(s, fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=_dt.timezone.utc)
                return dt.timestamp() * 1000.0
            except ValueError:
                continue
        raise MapperParsingException(
            f"failed to parse date field [{value}] for field [{self.name}]")


class IpFieldType(MappedFieldType):
    """IPv4/v6 stored as a 128-bit integer in a float64-safe pair; for
    simplicity v1 keeps the numeric form of IPv4 and hashes IPv6."""

    type_name = "ip"
    docvalue_kind = "numeric"

    def parse(self, value):
        import ipaddress
        try:
            return float(int(ipaddress.ip_address(str(value))))
        except ValueError:
            raise MapperParsingException(
                f"'{value}' is not an IP string literal.")


class DenseVectorFieldType(MappedFieldType):
    """ref: x-pack DenseVectorFieldMapper.java:44-47 — max 2048 dims, float
    values; here destined for the [n_docs, dim] device slab (bf16 on TPU)."""

    type_name = "dense_vector"
    docvalue_kind = "vector"
    MAX_DIMS = 2048

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.dims = int(self.params.get("dims", 0))
        if not (0 < self.dims <= self.MAX_DIMS):
            raise MapperParsingException(
                f"The number of dimensions for field [{name}] should be in "
                f"the range [1, {self.MAX_DIMS}] but was [{self.dims}]")
        self.similarity = self.params.get("similarity", "cosine")

    def parse(self, value):
        arr = np.asarray(value, dtype=np.float32)
        if arr.ndim != 1 or arr.shape[0] != self.dims:
            raise MapperParsingException(
                f"The [dims] of field [{self.name}] is [{self.dims}], "
                f"doesn't match the number of dimensions in the provided "
                f"value [{arr.shape}]")
        return arr


class JoinFieldType(MappedFieldType):
    """Parent/child relations within one index (ref: modules/parent-join
    ParentJoinFieldMapper — the join field indexes the relation name, and
    children additionally index the parent id under ``{field}#parent``;
    parent and children must share a shard via routing)."""

    type_name = "join"
    docvalue_kind = "join"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        rels = (params or {}).get("relations", {})
        self.relations = {p: (c if isinstance(c, list) else [c])
                          for p, c in rels.items()}

    def parent_of(self, child: str) -> Optional[str]:
        for parent, children in self.relations.items():
            if child in children:
                return parent
        return None

    def children_of(self, parent: str) -> List[str]:
        return self.relations.get(parent, [])

    def to_mapping(self):
        return {"type": "join", "relations": {
            p: (c[0] if len(c) == 1 else c)
            for p, c in self.relations.items()}}


class GeoPointFieldType(MappedFieldType):
    """Latitude/longitude point (ref: server GeoPointFieldMapper; parse
    formats in common/geo/GeoUtils.parseGeoPoint: object, "lat,lon" string,
    [lon, lat] array, geohash, WKT POINT).

    Columnar layout: each point lands in two numeric doc-value columns
    ``{field}.lat`` / ``{field}.lon`` so every geo predicate (distance,
    bbox, polygon) is elementwise array math on device."""

    type_name = "geo_point"
    docvalue_kind = "geo"

    def parse(self, value):
        from elasticsearch_tpu.common.geo import parse_geo_point
        return parse_geo_point(value)


class GeoShapeFieldType(MappedFieldType):
    """Arbitrary GeoJSON geometry (ref: x-pack spatial GeoShapeWithDocValuesFieldMapper
    + server AbstractShapeGeometryFieldMapper). Indexed as its bounding box
    in four numeric columns ``{field}.min_lat/.min_lon/.max_lat/.max_lon``;
    relation predicates run bbox-level on device, with exact host
    verification against the _source geometry for polygon relations."""

    type_name = "geo_shape"
    docvalue_kind = "geoshape"

    def parse(self, value):
        from elasticsearch_tpu.common.geo import shape_bbox
        if isinstance(value, str):
            raise MapperParsingException(
                f"geo_shape [{self.name}]: WKT input not supported, use GeoJSON")
        return shape_bbox(value)


class _RangeFieldType(MappedFieldType):
    """Base for range field types (ref: server RangeFieldMapper — stores
    [lo, hi] intervals queried by relation). Columnar layout: two numeric
    columns ``{field}.lo`` / ``{field}.hi`` so relation predicates are
    elementwise interval comparisons."""

    docvalue_kind = "range"
    value_type: MappedFieldType = None  # set per subclass

    def parse(self, value):
        if not isinstance(value, dict):
            raise MapperParsingException(
                f"error parsing field [{self.name}]: expected an object with "
                f"gt/gte/lt/lte bounds")
        vt = self.value_type(self.name)
        lo, hi = -np.inf, np.inf
        for k, v in value.items():
            if k in ("gte", "from"):
                lo = float(vt.parse(v))
            elif k == "gt":
                lo = np.nextafter(float(vt.parse(v)), np.inf)
            elif k in ("lte", "to"):
                hi = float(vt.parse(v))
            elif k == "lt":
                hi = np.nextafter(float(vt.parse(v)), -np.inf)
            else:
                raise MapperParsingException(
                    f"error parsing field [{self.name}]: unknown bound [{k}]")
        return (lo, hi)


class IntegerRangeFieldType(_RangeFieldType):
    type_name = "integer_range"
class LongRangeFieldType(_RangeFieldType):
    type_name = "long_range"
class FloatRangeFieldType(_RangeFieldType):
    type_name = "float_range"
class DoubleRangeFieldType(_RangeFieldType):
    type_name = "double_range"
class DateRangeFieldType(_RangeFieldType):
    type_name = "date_range"
class IpRangeFieldType(_RangeFieldType):
    type_name = "ip_range"


class WildcardFieldType(KeywordFieldType):
    """ref: x-pack wildcard field — keyword-like, optimized for mid-string
    wildcard matching (the reference accelerates with an ngram index; here
    the term dictionary scan in the wildcard/regexp queries serves, since
    term scans are columnar batch ops not per-doc iterations)."""

    type_name = "wildcard"


class ConstantKeywordFieldType(MappedFieldType):
    """ref: x-pack mapper-constant-keyword — one value for every doc of the
    index; docs may omit it, supplying a different value is rejected.
    Handled at query time (term/exists match all docs), nothing indexed."""

    type_name = "constant_keyword"
    docvalue_kind = "constant"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.value = self.params.get("value")

    def parse(self, value):
        if self.value is None:
            # first supplied value pins the constant (as in the reference)
            self.value = str(value)
            self.params["value"] = self.value
            return None
        if str(value) != self.value:
            raise MapperParsingException(
                f"[constant_keyword] field [{self.name}] only accepts values "
                f"that are equal to the value defined in the mappings "
                f"[{self.value}], but got [{value}]")
        return None


class RankFeatureFieldType(MappedFieldType):
    """ref: modules/mapper-extras RankFeatureFieldMapper — a positive float
    feature consumed by the rank_feature query (sat/log/sigmoid score)."""

    type_name = "rank_feature"
    docvalue_kind = "numeric"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.positive_score_impact = bool(
            self.params.get("positive_score_impact", True))

    def parse(self, value):
        v = float(value)
        if v <= 0:
            raise MapperParsingException(
                f"[rank_feature] fields do not support negative or zero "
                f"values, got [{v}] for field [{self.name}]")
        return v


class RankFeaturesFieldType(MappedFieldType):
    """ref: RankFeaturesFieldMapper — a sparse map of feature -> positive
    float; each key lands in its own numeric column ``{field}.{key}``."""

    type_name = "rank_features"
    docvalue_kind = "rank_features"

    def parse(self, value):
        if not isinstance(value, dict):
            raise MapperParsingException(
                f"[rank_features] field [{self.name}] expects an object")
        out = {}
        for k, v in value.items():
            if float(v) <= 0:
                raise MapperParsingException(
                    f"[rank_features] fields do not support negative or "
                    f"zero values, got [{v}] for feature [{k}]")
            out[str(k)] = float(v)
        return out


class FlattenedFieldType(MappedFieldType):
    """ref: x-pack mapper-flattened FlatObjectFieldMapper — a whole JSON
    object indexed as keyword terms: the root field matches any leaf value,
    ``{field}.{path}`` matches that key's values."""

    type_name = "flattened"
    docvalue_kind = "flattened"

    def parse(self, value):
        if not isinstance(value, dict):
            raise MapperParsingException(
                f"[flattened] field [{self.name}] expects an object")
        leaves: List[Tuple[str, str]] = []

        def walk(obj, prefix=""):
            for k, v in obj.items():
                p = f"{prefix}{k}"
                if isinstance(v, dict):
                    walk(v, f"{p}.")
                elif isinstance(v, list):
                    for item in v:
                        if isinstance(item, dict):
                            walk(item, f"{p}.")
                        else:
                            leaves.append((p, str(item)))
                else:
                    leaves.append((p, str(v)))

        walk(value)
        return leaves


class TokenCountFieldType(MappedFieldType):
    """ref: modules/mapper-extras TokenCountFieldMapper — indexes the
    number of analyzed tokens as a numeric column."""

    type_name = "token_count"
    docvalue_kind = "token_count"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.analyzer_name = self.params.get("analyzer", "standard")

    def parse(self, value):
        return str(value)


class Murmur3FieldType(MappedFieldType):
    """ref: plugins/mapper-murmur3 — stores the murmur3 hash of the value
    for cheap cardinality estimation."""

    type_name = "murmur3"
    docvalue_kind = "numeric"

    def parse(self, value):
        from elasticsearch_tpu.index.service import murmur3_hash
        return float(murmur3_hash(str(value)))


_ANNOTATION_RE = re.compile(r"\[([^\]\[]*)\]\(([^\)\(]*)\)")


def parse_annotated_text(text_plus_markup: str):
    """``"[John Smith](John%20Smith&Person)"`` → (plain text,
    [(start, end, [values])]) — the markdown-like annotation syntax of
    mapper-annotated-text (ref: plugins/mapper-annotated-text/.../
    AnnotatedTextFieldMapper.java:174-218 AnnotatedText.parse:
    url-decoded untyped values, ``&``-separated; ``key=value`` pairs
    are rejected)."""
    from urllib.parse import unquote
    plain: List[str] = []
    plain_len = 0
    annotations = []
    last = 0
    for m in _ANNOTATION_RE.finditer(text_plus_markup):
        if m.start() > last:
            seg = text_plus_markup[last:m.start()]
            plain.append(seg)
            plain_len += len(seg)
        start, anchor = plain_len, m.group(1)
        plain.append(anchor)
        plain_len += len(anchor)
        last = m.end()
        values = []
        for pair in m.group(2).split("&"):
            if "=" in pair:
                raise MapperParsingException(
                    "key=value pairs are not supported in annotations")
            if pair:
                values.append(unquote(pair))
        if values:
            annotations.append((start, plain_len, values))
    plain.append(text_plus_markup[last:])
    return "".join(plain), annotations


class AnnotatedTextFieldType(TextFieldType):
    """``annotated_text`` — text whose markup injects annotation terms
    at the anchor's token position (ref: mapper-annotated-text's
    AnnotationsInjector emitting annotation values as same-position
    synonym tokens over the anchor span)."""

    type_name = "annotated_text"


class SearchAsYouTypeFieldType(TextFieldType):
    """ref: modules/mapper-extras SearchAsYouTypeFieldMapper — a text field
    with shingle subfields ``._2gram`` / ``._3gram`` and an
    ``._index_prefix`` edge-ngram field feeding match_bool_prefix."""

    type_name = "search_as_you_type"
    docvalue_kind = "postings"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.max_shingle_size = int(self.params.get("max_shingle_size", 3))


class ShingleSubFieldType(TextFieldType):
    """Synthetic ``._Ngram`` subfield of search_as_you_type: queries analyze
    with the base analyzer then shingle to width N (not user-mappable,
    excluded from to_mapping)."""

    type_name = "text"

    def __init__(self, name, shingle_size: int, params=None):
        super().__init__(name, params)
        self.shingle_size = shingle_size


class PercolatorFieldType(MappedFieldType):
    """Stores a query for reverse search (ref: modules/percolator
    PercolatorFieldMapper — the query is kept in _source and re-parsed at
    percolate time against an in-memory index of the candidate docs).
    Invalid queries are rejected at index time, as in the reference."""

    type_name = "percolator"
    docvalue_kind = "stored_query"

    def parse(self, value):
        if not isinstance(value, dict):
            raise MapperParsingException(
                f"percolator field [{self.name}] expects a query object")
        from elasticsearch_tpu.search.queries import parse_query
        try:
            parse_query(value)
        except Exception as e:
            raise MapperParsingException(
                f"percolator field [{self.name}]: invalid query: {e}")
        return value


class CompletionFieldType(MappedFieldType):
    """ref: search/suggest/completion/CompletionFieldMapper — suggestion
    inputs with optional weights and category contexts, served by the
    weighted prefix index (index/segment.py CompletionValues; the
    reference builds NRT FSTs — CompletionSuggester.java:41). Accepts
    a string, a list of strings, or
    ``{"input": [...], "weight": N, "contexts": {name: [values]}}``."""

    type_name = "completion"
    docvalue_kind = "completion"

    def __init__(self, name, params=None):
        super().__init__(name, params)
        self.context_names = [c.get("name")
                              for c in self.params.get("contexts", [])
                              if isinstance(c, dict)]

    def parse(self, value):
        """Normalize to a list of (input, weight, contexts frozenset of
        'name=value' strings)."""
        entries = []
        specs = value if isinstance(value, list) and any(
            isinstance(v, dict) for v in value) else [value]
        for spec in specs:
            if isinstance(spec, str):
                entries.append((spec, 1.0, frozenset()))
                continue
            if isinstance(spec, list):
                entries.extend((str(s), 1.0, frozenset()) for s in spec)
                continue
            if not isinstance(spec, dict):
                raise MapperParsingException(
                    f"failed to parse completion field [{self.name}]")
            inputs = spec.get("input", [])
            if isinstance(inputs, str):
                inputs = [inputs]
            weight = float(spec.get("weight", 1.0))
            ctx = set()
            for cname, cvals in (spec.get("contexts") or {}).items():
                if isinstance(cvals, str):
                    cvals = [cvals]
                ctx.update(f"{cname}={v}" for v in cvals)
            entries.extend((str(i), weight, frozenset(ctx))
                           for i in inputs)
        return entries


FIELD_TYPES = {
    t.type_name: t for t in [
        CompletionFieldType,
        TextFieldType, KeywordFieldType, LongFieldType, IntegerFieldType,
        ShortFieldType, ByteFieldType, DoubleFieldType, FloatFieldType,
        HalfFloatFieldType, BooleanFieldType, DateFieldType, IpFieldType,
        DenseVectorFieldType, JoinFieldType, PercolatorFieldType,
        GeoPointFieldType, GeoShapeFieldType,
        IntegerRangeFieldType, LongRangeFieldType, FloatRangeFieldType,
        DoubleRangeFieldType, DateRangeFieldType, IpRangeFieldType,
        WildcardFieldType, ConstantKeywordFieldType, RankFeatureFieldType,
        RankFeaturesFieldType, TokenCountFieldType, Murmur3FieldType,
        SearchAsYouTypeFieldType, FlattenedFieldType,
        AnnotatedTextFieldType,
    ]
}

IntegerRangeFieldType.value_type = IntegerFieldType
LongRangeFieldType.value_type = LongFieldType
FloatRangeFieldType.value_type = FloatFieldType
DoubleRangeFieldType.value_type = DoubleFieldType
DateRangeFieldType.value_type = DateFieldType
IpRangeFieldType.value_type = IpFieldType


# ---------------------------------------------------------------------------
# Parsed document
# ---------------------------------------------------------------------------

@dataclass
class ParsedDocument:
    """The analogue of the reference's ParsedDocument/LuceneDocument: typed,
    columnar-ready values per field."""

    doc_id: str
    source: bytes
    # field -> list of Token (analyzed text)
    text_tokens: Dict[str, List[Token]] = field(default_factory=dict)
    # field -> list of untokenized terms
    keyword_terms: Dict[str, List[str]] = field(default_factory=dict)
    # field -> list of float64 values
    numeric_values: Dict[str, List[float]] = field(default_factory=dict)
    # field -> np.ndarray [dims] float32
    vectors: Dict[str, np.ndarray] = field(default_factory=dict)
    # field -> list of (input, weight, contexts) completion entries
    completion_entries: Dict[str, List[Any]] = field(default_factory=dict)
    # field -> similarity name (cosine | dot_product | l2_norm)
    vector_similarity: Dict[str, str] = field(default_factory=dict)
    # dynamic-mapping update discovered during parse (field -> mapping dict)
    dynamic_mappings: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    def field_length(self, fld: str) -> int:
        """Token count — the BM25 norm input (Lucene stores this quantized
        into a 1-byte norm; we keep the exact count, see SURVEY.md §7
        'Scoring parity')."""
        return len(self.text_tokens.get(fld, ()))


# ---------------------------------------------------------------------------
# Document mapper / parser
# ---------------------------------------------------------------------------

_DYNAMIC_DATE_RE = re.compile(r"\d{4}[-/]\d{2}[-/]\d{2}([T ].*)?$")


class DocumentMapper:
    """Holds the field-type map for one index (ref: DocumentMapper +
    RootObjectMapper flattened to dotted paths)."""

    def __init__(self, mappings: Optional[Dict[str, Any]] = None,
                 analysis: Optional[AnalysisRegistry] = None,
                 dynamic: str = "true"):
        self.fields: Dict[str, MappedFieldType] = {}
        self.analysis = analysis or AnalysisRegistry()
        self.dynamic = dynamic  # "true" | "false" | "strict"
        self.nested_paths: set = set()
        # ref: plugins/mapper-size — opt-in _size metadata field recording
        # the source byte length as a searchable/aggregatable numeric
        self.size_enabled = False
        if mappings:
            if "properties" in mappings:
                props = mappings["properties"]
            else:
                # properties-less shorthand: sibling meta keys like
                # "dynamic" are not field definitions
                props = {k: v for k, v in mappings.items()
                         if isinstance(v, dict)
                         and not k.startswith("_")}
            self._add_properties("", props)
            self.dynamic = str(mappings.get("dynamic", dynamic)).lower()
            size_spec = mappings.get("_size", {})
            if not isinstance(size_spec, dict):
                size_spec = {"enabled": size_spec}
            self.size_enabled = size_spec.get("enabled") in (True, "true")
            if self.size_enabled and "_size" not in self.fields:
                self.fields["_size"] = LongFieldType("_size")

    def _add_properties(self, prefix: str, props: Dict[str, Any]):
        for name, conf in props.items():
            path = f"{prefix}{name}"
            if "properties" in conf and "type" not in conf:
                self._add_properties(f"{path}.", conf["properties"])
                continue
            type_name = conf.get("type", "object")
            if type_name == "object":
                if "properties" in conf:
                    self._add_properties(f"{path}.", conf["properties"])
                continue
            if type_name == "nested":
                # nested objects index flattened (device coarse filter);
                # per-object correlation is restored by NestedQuery's
                # source-level verification (ref: nested docs are separate
                # Lucene documents in the reference — SURVEY.md §2.1
                # Mapping; here: filter-then-verify like phrases)
                self.nested_paths.add(path)
                if "properties" in conf:
                    self._add_properties(f"{path}.", conf["properties"])
                continue
            cls = FIELD_TYPES.get(type_name)
            if cls is None:
                raise MapperParsingException(
                    f"No handler for type [{type_name}] declared on field [{name}]")
            params = {k: v for k, v in conf.items() if k != "type"}
            ft = cls(path, params)
            self.fields[path] = ft
            # multi-fields (ref: the "fields" mapping parameter —
            # every value indexes into the parent AND each subfield)
            subnames = []
            for subname, subconf in (conf.get("fields") or {}).items():
                stype = (subconf or {}).get("type", "keyword")
                scls = FIELD_TYPES.get(stype)
                if scls is None:
                    raise MapperParsingException(
                        f"No handler for type [{stype}] declared on "
                        f"field [{name}.{subname}]")
                sft = scls(f"{path}.{subname}",
                           {k: v for k, v in (subconf or {}).items()
                            if k != "type"})
                self.fields[sft.name] = sft
                subnames.append(subname)
            ft.subfields = subnames
            if isinstance(ft, SearchAsYouTypeFieldType):
                for n in range(2, ft.max_shingle_size + 1):
                    sub = f"{path}._{n}gram"
                    self.fields[sub] = ShingleSubFieldType(sub, n)
                pre = f"{path}._index_prefix"
                self.fields[pre] = KeywordFieldType(pre)

    def to_mapping(self) -> Dict[str, Any]:
        props: Dict[str, Any] = {}
        # multi-field subfields re-emit inside their parent's "fields"
        # param (already in ft.params), not as standalone properties
        sub_paths = {f"{p}.{s}" for p, ft in self.fields.items()
                     for s in (getattr(ft, "subfields", ()) or ())}
        for path, ft in sorted(self.fields.items()):
            if isinstance(ft, ShingleSubFieldType) or path.endswith("._index_prefix"):
                continue  # synthetic search_as_you_type subfields
            if path in sub_paths:
                continue
            if path == "_size":
                continue  # metadata field, emitted as _size below
            node = props
            parts = path.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = ft.to_mapping()
        # nested paths re-emit their type so reloads restore semantics
        for npath in sorted(self.nested_paths):
            node = props
            parts = npath.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node.setdefault(parts[-1], {})["type"] = "nested"
        out: Dict[str, Any] = {"properties": props}
        if self.size_enabled:
            out["_size"] = {"enabled": True}
        return out

    # -- dynamic mapping (ref: DocumentParser dynamic templates default path)
    def _infer_type(self, path: str, value: Any) -> Optional[MappedFieldType]:
        if isinstance(value, bool):
            return BooleanFieldType(path)
        if isinstance(value, int):
            return LongFieldType(path)
        if isinstance(value, float):
            return FloatFieldType(path)
        if isinstance(value, str):
            if _DYNAMIC_DATE_RE.match(value):
                try:
                    DateFieldType(path).parse(value)
                    return DateFieldType(path)
                except MapperParsingException:
                    pass
            # ref: dynamic strings map to text with a .keyword subfield
            return TextFieldType(path)
        return None

    def parse(self, doc_id: str, source: Dict[str, Any]) -> ParsedDocument:
        parsed = ParsedDocument(
            doc_id=doc_id,
            source=json.dumps(source, separators=(",", ":")).encode(),
        )
        self._parse_object("", source, parsed)
        if self.size_enabled:
            parsed.numeric_values["_size"] = [float(len(parsed.source))]
        return parsed

    def join_parent_routing(self, source: Dict[str, Any]) -> Optional[str]:
        """Parent id if `source` is a child doc. Children MUST live on
        their parent's shard (ref: parent-join routing_required — ES
        rejects unrouted children with routing_missing_exception; here the
        parent id is derived as the routing key instead, which colocates
        the child with a default-routed parent and keeps internal re-index
        paths — _update_by_query, _reindex, shrink — working on join
        indices). O(1) when the mapping has no join field."""
        if not self._join_fields:
            return None
        for path in self._join_fields:
            cur: Any = source
            for part in path.split("."):
                if not isinstance(cur, dict) or part not in cur:
                    cur = None
                    break
                cur = cur[part]
            if isinstance(cur, dict) and cur.get("parent") is not None:
                return str(cur["parent"])
        return None

    @property
    def _join_fields(self) -> List[str]:
        cached = self.__dict__.get("_join_fields_cache")
        if cached is None or cached[0] != len(self.fields):
            cached = (len(self.fields),
                      [p for p, ft in self.fields.items()
                       if isinstance(ft, JoinFieldType)])
            self.__dict__["_join_fields_cache"] = cached
        return cached[1]

    def _parse_object(self, prefix: str, obj: Dict[str, Any], parsed: ParsedDocument):
        for key, value in obj.items():
            path = f"{prefix}{key}"
            ft_pre = self.fields.get(path)
            if ft_pre is not None and isinstance(ft_pre, JoinFieldType):
                # {"name": rel} / {"name": rel, "parent": id} / "rel"
                if isinstance(value, str):
                    rel, parent = value, None
                elif isinstance(value, dict):
                    rel, parent = value.get("name"), value.get("parent")
                else:
                    raise MapperParsingException(
                        f"failed to parse join field [{path}]")
                known = set(ft_pre.relations) | {
                    c for cs in ft_pre.relations.values() for c in cs}
                if rel not in known:
                    raise MapperParsingException(
                        f"unknown join name [{rel}] for field [{path}]")
                if parent is None and ft_pre.parent_of(rel) is not None:
                    raise MapperParsingException(
                        f"[parent] is missing for join field [{path}]")
                parsed.keyword_terms.setdefault(path, []).append(rel)
                if parent is not None:
                    parsed.keyword_terms.setdefault(
                        f"{path}#parent", []).append(str(parent))
                continue
            if ft_pre is not None and isinstance(ft_pre, PercolatorFieldType):
                ft_pre.parse(value)  # validate shape; query stays in _source
                continue
            if ft_pre is not None and ft_pre.docvalue_kind in (
                    "geo", "geoshape", "range", "rank_features",
                    "flattened", "completion"):
                # object-valued field types must not recurse as sub-objects
                if ft_pre.docvalue_kind == "geo":
                    from elasticsearch_tpu.common.geo import is_point_value
                    values = [value] if is_point_value(value) else list(value)
                else:
                    values = (list(value) if isinstance(value, (list, tuple))
                              else [value])
                self._index_values(ft_pre, values, parsed)
                continue
            if isinstance(value, dict):
                self._parse_object(f"{path}.", value, parsed)
                continue
            ft_known = self.fields.get(path)
            if ft_known is not None and ft_known.docvalue_kind == "vector":
                # a dense_vector's JSON array is ONE value, not multi-values
                values = [value]
            else:
                values = value if isinstance(value, list) else [value]
            # arrays of objects flatten (nested type is a later addition)
            if values and isinstance(values[0], dict):
                for v in values:
                    self._parse_object(f"{path}.", v, parsed)
                continue
            ft = self.fields.get(path)
            if ft is None:
                if self.dynamic == "strict":
                    raise StrictDynamicMappingException(
                        f"mapping set to strict, dynamic introduction of "
                        f"[{path}] within [_doc] is not allowed")
                if self.dynamic == "false":
                    continue
                sample = next((v for v in values if v is not None), None)
                if sample is None:
                    continue
                ft = self._infer_type(path, sample)
                if ft is None:
                    continue
                self.fields[path] = ft
                parsed.dynamic_mappings[path] = ft.to_mapping()
                if isinstance(ft, TextFieldType):
                    kw = KeywordFieldType(f"{path}.keyword", {"ignore_above": 256})
                    self.fields[kw.name] = kw
                    parsed.dynamic_mappings[kw.name] = kw.to_mapping()
            self._index_values(ft, values, parsed)
            # explicit multi-fields: the same values index into every
            # declared subfield
            subs = getattr(ft, "subfields", ()) or ()
            for subname in subs:
                sft = self.fields.get(f"{ft.name}.{subname}")
                if sft is not None:
                    self._index_values(sft, values, parsed)
            # copy_to: values additionally index into the target
            # field(s) (ref: the copy_to mapping parameter)
            copy_to = ft.params.get("copy_to")
            if copy_to:
                targets = ([copy_to] if isinstance(copy_to, str)
                           else copy_to)
                for tgt in targets:
                    tft = self.fields.get(tgt)
                    if tft is not None and tft is not ft:
                        self._index_values(tft, values, parsed)
            # dynamic text fields also index into their .keyword subfield
            kw_ft = self.fields.get(f"{ft.name}.keyword")
            if (kw_ft is not None and isinstance(ft, TextFieldType)
                    and "keyword" not in subs):
                self._index_values(kw_ft, values, parsed)

    def _index_shingles(self, ft: "SearchAsYouTypeFieldType",
                        toks: List[Token], parsed: ParsedDocument):
        """Index ``._2gram``/``._3gram`` shingle subfields and the
        ``._index_prefix`` edge-ngram field (ref: SearchAsYouTypeFieldMapper
        shingle + prefix subfields feeding match_bool_prefix /
        multi_match type bool_prefix)."""
        terms = [t.term for t in toks]
        for n in range(2, ft.max_shingle_size + 1):
            sub = f"{ft.name}._{n}gram"
            out = parsed.text_tokens.setdefault(sub, [])
            base = out[-1].position + 100 if out else 0
            for i in range(len(terms) - n + 1):
                out.append(Token(" ".join(terms[i:i + n]), base + i, -1, -1))
        prefixes = parsed.keyword_terms.setdefault(f"{ft.name}._index_prefix", [])
        for term in terms:
            for plen in range(1, min(len(term), 20) + 1):
                prefixes.append(term[:plen])

    def _index_values(self, ft: MappedFieldType, values: List[Any],
                      parsed: ParsedDocument):
        for value in values:
            if value is None:
                continue
            typed = ft.parse(value)
            if typed is None:
                continue
            if ft.docvalue_kind == "postings":
                analyzer = self.analysis.get(ft.analyzer_name) if self.analysis.has(
                    ft.analyzer_name) else self.analysis.default
                annotations = []
                if isinstance(ft, AnnotatedTextFieldType):
                    typed, annotations = parse_annotated_text(typed)
                toks = parsed.text_tokens.setdefault(ft.name, [])
                base = toks[-1].position + 100 if toks else 0  # position gap between values
                new_toks = [Token(t.term, base + t.position, t.start_offset,
                                  t.end_offset) for t in analyzer.analyze(typed)]
                toks.extend(new_toks)
                # annotation values become same-position tokens over the
                # anchor span (ref: AnnotationsInjector — searching the
                # annotation matches where the anchor text matched);
                # the appended slice re-sorts by position because the
                # postings writer expects per-doc positions in order
                if annotations:
                    n_text = len(new_toks)
                    for start, end, ann_values in annotations:
                        anchor = [t for t in new_toks
                                  if t.start_offset >= start
                                  and t.end_offset <= end]
                        pos = (anchor[0].position if anchor
                               else (new_toks[-1].position + 1
                                     if new_toks else base))
                        toks.extend(Token(v, pos, start, end)
                                    for v in ann_values)
                    tail = sorted(toks[len(toks) - n_text
                                       - sum(len(v) for _, _, v in
                                             annotations):],
                                  key=lambda t: t.position)
                    toks[len(toks) - len(tail):] = tail
                if isinstance(ft, SearchAsYouTypeFieldType):
                    self._index_shingles(ft, new_toks, parsed)
            elif ft.docvalue_kind == "completion":
                parsed.completion_entries.setdefault(
                    ft.name, []).extend(typed)
            elif ft.docvalue_kind == "term":
                parsed.keyword_terms.setdefault(ft.name, []).append(typed)
            elif ft.docvalue_kind == "numeric":
                parsed.numeric_values.setdefault(ft.name, []).append(float(typed))
            elif ft.docvalue_kind == "range":
                lo, hi = typed
                parsed.numeric_values.setdefault(f"{ft.name}.lo", []).append(lo)
                parsed.numeric_values.setdefault(f"{ft.name}.hi", []).append(hi)
            elif ft.docvalue_kind == "rank_features":
                for feat, v in typed.items():
                    parsed.numeric_values.setdefault(
                        f"{ft.name}.{feat}", []).append(v)
            elif ft.docvalue_kind == "flattened":
                for path, term in typed:
                    parsed.keyword_terms.setdefault(ft.name, []).append(term)
                    parsed.keyword_terms.setdefault(
                        f"{ft.name}.{path}", []).append(term)
            elif ft.docvalue_kind == "token_count":
                analyzer = (self.analysis.get(ft.analyzer_name)
                            if self.analysis.has(ft.analyzer_name)
                            else self.analysis.default)
                parsed.numeric_values.setdefault(ft.name, []).append(
                    float(len(analyzer.analyze(typed))))
            elif ft.docvalue_kind == "geo":
                lat, lon = typed
                parsed.numeric_values.setdefault(f"{ft.name}.lat", []).append(lat)
                parsed.numeric_values.setdefault(f"{ft.name}.lon", []).append(lon)
            elif ft.docvalue_kind == "geoshape":
                min_lat, min_lon, max_lat, max_lon = typed
                for suffix, v in (("min_lat", min_lat), ("min_lon", min_lon),
                                  ("max_lat", max_lat), ("max_lon", max_lon)):
                    parsed.numeric_values.setdefault(
                        f"{ft.name}.{suffix}", []).append(v)
            elif ft.docvalue_kind == "vector":
                parsed.vectors[ft.name] = typed
                parsed.vector_similarity[ft.name] = ft.similarity


class MapperService:
    """Per-index mapping lifecycle: merge updates, expose field types
    (ref: index/mapper/MapperService.java merge/documentMapper)."""

    def __init__(self, index_settings: Settings = Settings.EMPTY,
                 mappings: Optional[Dict[str, Any]] = None):
        self.analysis = AnalysisRegistry(index_settings)
        self._lock = threading.Lock()
        self.mapper = DocumentMapper(mappings, self.analysis)

    def field_type(self, name: str) -> Optional[MappedFieldType]:
        return self.mapper.fields.get(name)

    def field_names(self) -> List[str]:
        return sorted(self.mapper.fields)

    def merge(self, new_mappings: Dict[str, Any]):
        """Merge a mapping update; conflicting type changes are rejected
        (ref: MapperService.merge MergeReason.MAPPING_UPDATE)."""
        with self._lock:
            incoming = DocumentMapper(new_mappings, self.analysis)
            for path, ft in incoming.fields.items():
                existing = self.mapper.fields.get(path)
                if existing is not None and existing.type_name != ft.type_name:
                    raise IllegalArgumentException(
                        f"mapper [{path}] cannot be changed from type "
                        f"[{existing.type_name}] to [{ft.type_name}]")
            self.mapper.fields.update(incoming.fields)

    def parse(self, doc_id: str, source: Dict[str, Any]) -> ParsedDocument:
        return self.mapper.parse(doc_id, source)

    def to_mapping(self) -> Dict[str, Any]:
        return self.mapper.to_mapping()
