"""Serving-front kernels: exact batched BM25 top-k with exact totals.

The native HTTP front (native/src/estpu_http.cpp) parses hot `_search`
bodies in C++ and hands Python per-cohort term-id batches; this module is
the device half of that path. One launch scores a whole cohort — plain
matches AND bool+filter queries together via a per-query mask column
index — and returns a SINGLE packed f32 array so the (degraded-tunnel)
device→host sync is paid once per cohort (ops/bm25.py:119-131 documents
the readback cliff).

Exactness (VERDICT round 2 item 2 — the contract is exact top-k, ref
TopDocsCollectorContext.java:210-217):
- no block-max pruning: the full selected postings go through the sort;
- the per-doc segmented sum uses a DOUBLING scan over the docid-sorted
  runs (Hillis-Steele with the key-equality carry — valid because runs
  are contiguous after the sort), NOT a global cumsum: a float32 prefix
  over 500K postings carries an absolute error ~ prefix·2^-24 that
  reorders top-1000 boundary docs (measured recall 0.9969); the doubling
  scan sums each doc's ≤MAX_TERMS contributions at full f32 accuracy —
  the same arithmetic as the CPU baseline — and is cheaper than
  cumsum+cummax anyway (5 shifted adds).

Totals are exact distinct-match counts (relation "eq"), matching the
dense path's `scores > 0` semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops.bm25 import _SENTINEL, bm25_contrib

# mask-stack height: every cohort launch carries F dense bool columns
# (row 0 = the plain live mask; rows 1.. = cached filter-set columns);
# each query picks its row, so mixed filtered/unfiltered traffic shares
# ONE launch instead of fragmenting per filter set.
F_SLOTS = 8

# covers docid-runs up to 2^5 = 32 postings — a query has ≤16 tokens
# (estpu_http.cpp MAX_TERMS), each contributing ≤1 posting per doc, so
# 5 doubling steps always close every real run (sentinel runs are longer
# but their totals are never read).
_SCAN_STEPS = (1, 2, 4, 8, 16)


def _topk_total(block_docids, block_tfs, sel_blocks, sel_weights,
                doc_lens, live_col, avg_len, k1: float, b: float, k: int):
    """Single query: (values [k], docids [k], total []) — sort by docid,
    doubling segmented sum, top-k at run-last positions."""
    d = jnp.take(block_docids, sel_blocks, axis=0)       # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    dl = jnp.take(doc_lens, d)
    contrib = bm25_contrib(sel_weights, tf, dl, avg_len, k1, b)

    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = (tf.reshape(-1) > 0.0) & jnp.take(live_col, dflat)
    dkey = jnp.where(valid, dflat, _SENTINEL)
    cflat = jnp.where(valid, cflat, 0.0)

    sorted_k, sorted_c = jax.lax.sort((dkey, cflat), num_keys=1)
    # segmented inclusive scan by doubling: runs are contiguous, so
    # key[i-d] == key[i] implies the whole [i-d, i] span is one run
    x = sorted_c
    for step in _SCAN_STEPS:
        prev_x = jnp.pad(x[:-step], (step, 0))
        prev_k = jnp.pad(sorted_k[:-step], (step, 0),
                         constant_values=-1)
        x = x + jnp.where(prev_k == sorted_k, prev_x, 0.0)
    nxt = jnp.concatenate([sorted_k[1:],
                           jnp.full(1, -1, sorted_k.dtype)])
    is_last = sorted_k != nxt
    real_last = is_last & (x > 0.0) & (sorted_k != _SENTINEL)
    cand = jnp.where(real_last, x, -jnp.inf)
    total = real_last.sum(dtype=jnp.int32)
    # STABLE top-k: TPU top_k does not break exact-score ties by lowest
    # index, but the exactness contract (and Lucene, and the CPU
    # baseline) takes the LOWEST DOCID among boundary ties — with
    # integer tfs/lengths, dozens of docs can tie bit-exactly at the
    # kth score. Phase 1 finds the kth value; phase 2 keeps every doc
    # above it plus the first (lowest-docid — cand is docid-ordered)
    # ties at it, exactly filling k.
    vals1, _ = jax.lax.top_k(cand, k)
    kth = vals1[k - 1]
    gt = cand > kth
    eq = cand == kth
    t_need = k - gt.sum()
    eq_rank = jnp.cumsum(eq.astype(jnp.int32))
    cand2 = jnp.where(gt | (eq & (eq_rank <= t_need)), cand, -jnp.inf)
    vals, pos = jax.lax.top_k(cand2, k)
    ids = jnp.take(sorted_k, pos)
    ids = jnp.where(jnp.isfinite(vals), ids, _SENTINEL)
    return vals, ids, total


@partial(jax.jit, static_argnames=("k1", "b", "k"))
def bm25_topk_total_batch(block_docids,   # int32 [TB, B]
                          block_tfs,      # float32 [TB, B]
                          sel_blocks,     # int32 [Q, NB]
                          sel_weights,    # float32 [Q, NB]
                          doc_lens,       # float32 [ND]
                          masks,          # bool [F_SLOTS, ND]
                          mask_ids,       # int32 [Q] row into masks
                          avg_len, k1: float, b: float, k: int):
    """Cohort launch → ONE packed float32 [Q, 2k+1]:
    ``row = [values (k) | docids bitcast to f32 (k) | total bitcast (1)]``.
    Unpack host-side with ``row[k:].view(np.int32)``."""
    def one(s, w, mid):
        live_col = jnp.take(masks, mid, axis=0)
        return _topk_total(block_docids, block_tfs, s, w, doc_lens,
                           live_col, avg_len, k1, b, k)

    vals, ids, totals = jax.vmap(one)(sel_blocks, sel_weights, mask_ids)
    ids_f = jax.lax.bitcast_convert_type(ids, jnp.float32)
    tot_f = jax.lax.bitcast_convert_type(totals, jnp.float32)
    return jnp.concatenate([vals, ids_f, tot_f[:, None]], axis=1)
