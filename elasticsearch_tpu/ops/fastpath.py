"""Serving-front kernels: exact batched BM25 top-k with exact totals.

The native HTTP front (native/src/estpu_http.cpp) parses hot `_search`
bodies in C++ and hands Python per-cohort term-id batches; this module is
the device half of that path. One launch scores a whole cohort and returns
a SINGLE packed f32 array so the (degraded-tunnel) device→host sync is paid
once per cohort, not once per output (ops/bm25.py:119-131 documents the
readback cliff).

Exactness: no block-max pruning here — the full selected postings go
through the sort, so recall vs an exact scorer is 1.0 by construction
(VERDICT round 2: the pruned plan path's 0.99 recall was the gap; the
baseline contract is exact top-k, ref TopDocsCollectorContext.java:210-217).
Totals are exact distinct-match counts (relation "eq"), matching the dense
path's `scores > 0` semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops.bm25 import _SENTINEL, bm25_contrib


def _topk_total(block_docids, block_tfs, sel_blocks, sel_weights,
                doc_lens, live, avg_len, k1: float, b: float, k: int):
    """Single query: (values [k], docids [k], total []) — the sorted
    segmented-reduction top-k (ops/bm25.bm25_sorted_topk) plus an exact
    distinct-match count from the same run boundaries."""
    d = jnp.take(block_docids, sel_blocks, axis=0)       # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    dl = jnp.take(doc_lens, d)
    contrib = bm25_contrib(sel_weights, tf, dl, avg_len, k1, b)

    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = (tf.reshape(-1) > 0.0) & jnp.take(live, dflat)
    dkey = jnp.where(valid, dflat, _SENTINEL)
    cflat = jnp.where(valid, cflat, 0.0)

    sorted_k, sorted_c = jax.lax.sort((dkey, cflat), num_keys=1)
    cs = jnp.cumsum(sorted_c)
    cs_excl = cs - sorted_c
    prev = jnp.concatenate([jnp.full(1, -1, sorted_k.dtype),
                            sorted_k[:-1]])
    nxt = jnp.concatenate([sorted_k[1:],
                           jnp.full(1, -1, sorted_k.dtype)])
    is_first = sorted_k != prev
    is_last = sorted_k != nxt
    run_start_excl = jax.lax.cummax(jnp.where(is_first, cs_excl, 0.0))
    totals = cs - run_start_excl
    real_last = is_last & (totals > 0.0) & (sorted_k != _SENTINEL)
    cand = jnp.where(real_last, totals, -jnp.inf)
    total = real_last.sum(dtype=jnp.int32)
    vals, pos = jax.lax.top_k(cand, k)
    ids = jnp.take(sorted_k, pos)
    ids = jnp.where(jnp.isfinite(vals), ids, _SENTINEL)
    return vals, ids, total


@partial(jax.jit, static_argnames=("k1", "b", "k"))
def bm25_topk_total_batch(block_docids,   # int32 [TB, B]
                          block_tfs,      # float32 [TB, B]
                          sel_blocks,     # int32 [Q, NB]
                          sel_weights,    # float32 [Q, NB]
                          doc_lens,       # float32 [ND]
                          live,           # bool [ND] (base live AND filters)
                          avg_len, k1: float, b: float, k: int):
    """Cohort launch → ONE packed float32 [Q, 2k+1]:
    ``row = [values (k) | docids bitcast to f32 (k) | total bitcast (1)]``.
    Unpack host-side with ``row[k:].view(np.int32)``."""
    vals, ids, totals = jax.vmap(
        lambda s, w: _topk_total(block_docids, block_tfs, s, w,
                                 doc_lens, live, avg_len, k1, b, k)
    )(sel_blocks, sel_weights)
    ids_f = jax.lax.bitcast_convert_type(ids, jnp.float32)
    tot_f = jax.lax.bitcast_convert_type(totals, jnp.float32)
    return jnp.concatenate([vals, ids_f, tot_f[:, None]], axis=1)
