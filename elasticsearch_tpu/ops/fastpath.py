"""Serving-front kernels: exact batched BM25 top-k with exact totals.

The native HTTP front (native/src/estpu_http.cpp) parses hot `_search`
bodies in C++ and hands Python per-cohort term-id batches; this module is
the device half of that path. One launch scores a whole cohort — plain
matches AND bool+filter queries together via a per-query mask column
index — and returns a SINGLE packed f32 array so the (degraded-tunnel)
device→host sync is paid once per cohort (ops/bm25.py:119-131 documents
the readback cliff).

Exactness (VERDICT round 2 item 2 — the contract is exact top-k, ref
TopDocsCollectorContext.java:210-217):
- no block-max pruning: the full selected postings go through the sort;
- the per-doc segmented sum uses a DOUBLING scan over the docid-sorted
  runs (Hillis-Steele with the key-equality carry — valid because runs
  are contiguous after the sort), NOT a global cumsum: a float32 prefix
  over 500K postings carries an absolute error ~ prefix·2^-24 that
  reorders top-1000 boundary docs (measured recall 0.9969); the doubling
  scan sums each doc's ≤MAX_TERMS contributions at full f32 accuracy —
  the same arithmetic as the CPU baseline — and is cheaper than
  cumsum+cummax anyway (5 shifted adds).

Totals are exact distinct-match counts (relation "eq"), matching the
dense path's `scores > 0` semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops.bm25 import _SENTINEL, bm25_contrib
from elasticsearch_tpu.ops.plan import check_packed_id_limit
from elasticsearch_tpu.telemetry.engine import tracked_jit

# mask-stack height: every cohort launch carries F dense bool columns
# (row 0 = the plain live mask; rows 1.. = cached filter-set columns);
# each query picks its row, so mixed filtered/unfiltered traffic shares
# ONE launch instead of fragmenting per filter set. 32 (was 8): the
# kernel reads ONE row per query regardless, and the r3 bool+filters
# bench (28 distinct filter pairs from an 8-filter pool) fragmented
# cohorts to ~8-10 queries under the old 7-distinct-set launch budget —
# the dominant share of its 31.7-qps collapse (VERDICT r3 item 2).
F_SLOTS = 32

# covers docid-runs up to 2^5 = 32 postings — a query has ≤16 tokens
# (estpu_http.cpp MAX_TERMS), each contributing ≤1 posting per doc, so
# 5 doubling steps always close every real run (sentinel runs are longer
# but their totals are never read).
_SCAN_STEPS = (1, 2, 4, 8, 16)


def _score_dtype():
    """float64 when x64 is enabled: the f32 representation itself is
    the recall floor at corpus scale (at 2M docs, boundary score
    classes separated by <2^-24 relative collapse — measured recall
    0.999 in f32 vs 1.0 in f64; the CPU baseline accumulates in double
    too). Measured cost on chip: ~2% per launch (sort keys stay i32;
    only the payload/scan/top-k widen). Ranking runs in this dtype;
    reported scores stay float32 (the Lucene score type)."""
    import jax
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _doubling_scan(keys, vals, steps=_SCAN_STEPS):
    """Segmented inclusive sums over contiguous key-runs along the LAST
    axis (Hillis-Steele with the key-equality carry; run length must be
    covered by ``steps`` — see _SCAN_STEPS). Shared by every serving
    kernel so the precision contract lives in one place."""
    x = vals
    nd = keys.ndim
    for step in steps:
        pw = [(0, 0)] * (nd - 1) + [(step, 0)]
        prev_x = jnp.pad(x[..., :-step], pw)
        prev_k = jnp.pad(keys[..., :-step], pw, constant_values=-1)
        x = x + jnp.where(prev_k == keys, prev_x, 0.0)
    return x


def _stable_topk(cand, keys, k: int, bound_slot: bool = False):
    """STABLE top-k of ``cand`` [P] with the exactness contract's tie
    order: ``cand`` is key-ascending-ordered, so keeping the FIRST ties
    at the kth value takes the LOWEST keys (Lucene/CPU-baseline
    semantics — TPU top_k alone breaks ties arbitrarily). Returns
    (vals, ids) in cand's dtype; with ``bound_slot`` also the (k+1)th
    value (the v2 certificate's exclusion bound)."""
    vals1 = jax.lax.top_k(cand, k + 1 if bound_slot else k)[0]
    kth = vals1[k - 1]
    gt = cand > kth
    eq = cand == kth
    need = k - gt.sum()
    eq_rank = jnp.cumsum(eq.astype(jnp.int32))
    cand2 = jnp.where(gt | (eq & (eq_rank <= need)), cand, -jnp.inf)
    vals, pos = jax.lax.top_k(cand2, k)
    ids = jnp.take(keys, pos)
    ids = jnp.where(jnp.isfinite(vals), ids, _SENTINEL)
    if bound_slot:
        return vals, ids, vals1[k]
    return vals, ids


def _run_last_candidates(mk, x):
    """(cand, totals) from merged keys + per-run sums (batched [Q, P]):
    run-last positions carry the doc totals; everything else -inf."""
    q = mk.shape[0]
    nxt = jnp.concatenate([mk[:, 1:], jnp.full((q, 1), -1, mk.dtype)],
                          axis=1)
    real_last = (mk != nxt) & (x > 0.0) & (mk != _SENTINEL)
    totals = real_last.sum(axis=1, dtype=jnp.int32)
    return jnp.where(real_last, x, -jnp.inf), totals


def _topk_total(block_docids, block_tfs, sel_blocks, sel_weights,
                doc_lens, live_col, avg_len, k1: float, b: float, k: int):
    """Single query: (values [k], docids [k], total []) — sort by docid,
    doubling segmented sum, top-k at run-last positions."""
    # trace-time guard (shapes are static under jit): every serving
    # kernel reads ids back float-packed, which is exact only < 2^24
    check_packed_id_limit(doc_lens.shape[0], "fastpath kernel")
    dt = _score_dtype()
    d = jnp.take(block_docids, sel_blocks, axis=0)       # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0).astype(dt)
    dl = jnp.take(doc_lens, d).astype(dt)
    contrib = bm25_contrib(sel_weights.astype(dt), tf, dl,
                           jnp.asarray(avg_len, dt), k1, b)

    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = (tf.reshape(-1) > 0.0) & jnp.take(live_col, dflat)
    dkey = jnp.where(valid, dflat, _SENTINEL)
    cflat = jnp.where(valid, cflat, jnp.asarray(0.0, dt))

    sorted_k, sorted_c = jax.lax.sort((dkey, cflat), num_keys=1)
    x = _doubling_scan(sorted_k, sorted_c)
    cand, total = _run_last_candidates(sorted_k[None, :], x[None, :])
    cand, total = cand[0], total[0]
    vals, ids = _stable_topk(cand, sorted_k, k)
    return vals.astype(jnp.float32), ids, total


# ---------------------------------------------------------------------------
# θ-cached exact MaxScore: the repeat-query fast lane.
#
# The full kernel drags every selected posting through the sort — at 4096
# blocks that is 524K lanes per query, the device-bound ceiling of the
# serving path. MaxScore (the CPU baseline's own algorithm, ref: Lucene
# MaxScoreBulkScorer) splits query terms by their maximum possible
# contribution against a top-k threshold θ: docs in no ESSENTIAL term's
# postings provably can't reach θ, so only essential postings enter the
# sort; non-essential contributions are patched back per CANDIDATE by
# binary search in the term's (sorted) postings range. θ here is the
# exact kth score CACHED from a previous full run of the same query on
# the same immutable segment — a true lower bound by construction.
# Exactness is certified ON DEVICE: candidates beyond the top-C carry
# ess_(C+1) + Σ maxc_ne as an upper bound; if the patched kth doesn't
# strictly beat it, the flag trips and the host refires the full kernel.
# ---------------------------------------------------------------------------

NE_SLOTS = 8          # non-essential term slots (pad with len 0)
# candidates patched per query: must exceed the ESSENTIAL-union size of
# typical queries for the certificate to close (overflow bound is the
# (C+1)th essential score + Σ maxc_ne; at 4096 the r5 full bench
# refired 14 of 18 lane attempts — bursty 2M-doc unions run deep).
# Patch cost is 8 flat gathers x C lanes — trivial device work.
CAND = 16384


def _essential_phase1(block_docids, block_tfs, sel_blocks, sel_weights,
                      doc_lens, live_col, ne_bound, avg_len,
                      k1: float, b: float):
    """Exact scores over the ESSENTIAL union (the full kernel's sorted
    segmented-reduction at a smaller NB) → top-C candidates plus the
    overflow bound. Shared by BOTH patch lanes (binary-search and
    dense-table) so the exactness-critical candidate extraction has one
    definition. Returns (cand_ids [C], ess [C], overflow_bound [])."""
    check_packed_id_limit(doc_lens.shape[0], "fastpath essential lane")
    dt = _score_dtype()
    d = jnp.take(block_docids, sel_blocks, axis=0)
    tf = jnp.take(block_tfs, sel_blocks, axis=0).astype(dt)
    dl = jnp.take(doc_lens, d).astype(dt)
    contrib = bm25_contrib(sel_weights.astype(dt), tf, dl,
                           jnp.asarray(avg_len, dt), k1, b)
    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = (tf.reshape(-1) > 0.0) & jnp.take(live_col, dflat)
    dkey = jnp.where(valid, dflat, _SENTINEL)
    cflat = jnp.where(valid, cflat, jnp.asarray(0.0, dt))
    sorted_k, sorted_c = jax.lax.sort((dkey, cflat), num_keys=1)
    x = _doubling_scan(sorted_k, sorted_c)
    cand, _tot = _run_last_candidates(sorted_k[None, :], x[None, :])
    cand = cand[0]
    # top C+1: the (C+1)th essential score feeds the exactness bound.
    # C adapts down when the essential union itself is smaller than
    # CAND (small buckets / test corpora) — top_k k can't exceed lanes.
    c = min(CAND, int(cand.shape[0]) - 1)
    ess_vals, pos = jax.lax.top_k(cand, c + 1)
    cand_ids = jnp.take(sorted_k, pos)[:c]
    ess = ess_vals[:c]
    overflow_bound = ess_vals[c] + ne_bound   # -inf when exhausted
    return cand_ids, ess, overflow_bound


def _essential_epilogue(patched, cand_ids, overflow_bound, k: int):
    """Exact ordering over the candidate set + the on-device exactness
    certificate — ONE definition for both patch lanes. Rank by the
    REPORTED float32 score with docid-ascending ties (the full kernel's
    contract), certify kth (full precision, min over the selected k so
    f32 rounding can't certify upward) STRICTLY beats the overflow
    bound. Returns (vals [k] f32, ids [k], ok [])."""
    dt = _score_dtype()
    disp = patched.astype(jnp.float32)
    neg = jnp.where(jnp.isfinite(disp), -disp,
                    jnp.asarray(jnp.inf, jnp.float32))
    tie_ids = jnp.where(jnp.isfinite(disp), cand_ids, _SENTINEL)
    _skey, sids, svals, sdt = jax.lax.sort(
        (neg, tie_ids, disp, patched.astype(dt)), num_keys=2)
    out_vals = svals[:k]
    out_ids = jnp.where(jnp.isfinite(out_vals), sids[:k], _SENTINEL)
    kth = jnp.min(jnp.where(jnp.isfinite(out_vals), sdt[:k],
                            jnp.asarray(jnp.inf, dt)))
    kth = jnp.where(jnp.isfinite(out_vals[k - 1]), kth,
                    jnp.asarray(-jnp.inf, dt))
    # every doc outside the top-C candidates is bounded by
    # ess_(C+1)+Σmaxc_ne; STRICT inequality so boundary ties refire
    ok = jnp.asarray(
        (overflow_bound < kth) | ~jnp.isfinite(overflow_bound),
        jnp.int32)
    return out_vals, out_ids, ok


def _essential_one(block_docids, block_tfs, flat_docids, flat_tfs,
                   sel_blocks, sel_weights, doc_lens, live_col,
                   ne_start, ne_len, ne_idf, ne_bound,
                   avg_len, k1: float, b: float, k: int):
    dt = _score_dtype()
    cand_ids, ess, overflow_bound = _essential_phase1(
        block_docids, block_tfs, sel_blocks, sel_weights, doc_lens,
        live_col, ne_bound, avg_len, k1, b)

    # ---- phase 2: patch non-essential contributions per candidate
    safe_ids = jnp.clip(cand_ids, 0, doc_lens.shape[0] - 1)
    cdl = jnp.take(doc_lens, safe_ids).astype(dt)
    cnorm = k1 * (1.0 - b + b * cdl / jnp.asarray(avg_len, dt))
    patched = jnp.where(jnp.isfinite(ess), ess,
                        jnp.asarray(-jnp.inf, dt))
    n_flat = flat_docids.shape[0]
    for ti in range(NE_SLOTS):
        lo0 = ne_start[ti]
        ln = ne_len[ti]
        lo = jnp.full(cand_ids.shape, lo0, jnp.int32)
        hi = jnp.full(cand_ids.shape, lo0 + ln, jnp.int32)
        # 21 halving steps cover ranges to 2^21 postings per term —
        # the host refuses longer ne ranges (search/fastpath.py
        # _essential_split NE_MAX_LEN)
        for _ in range(21):
            mid = (lo + hi) // 2
            v = jnp.take(flat_docids, jnp.clip(mid, 0, n_flat - 1))
            go_right = v < cand_ids
            lo = jnp.where(go_right, mid + 1, lo)
            hi = jnp.where(go_right, hi, mid)
        in_range = (lo < lo0 + ln) & (ln > 0)
        at = jnp.clip(lo, 0, n_flat - 1)
        found = in_range & (jnp.take(flat_docids, at) == cand_ids)
        ptf = jnp.where(found,
                        jnp.take(flat_tfs, at).astype(dt), 0.0)
        add = jnp.where(ptf > 0.0,
                        ne_idf[ti].astype(dt) * ptf / (ptf + cnorm),
                        0.0)
        patched = jnp.where(jnp.isfinite(patched), patched + add,
                            patched)

    return _essential_epilogue(patched, cand_ids, overflow_bound, k)


@tracked_jit(static_argnames=("k1", "b", "k"))
def bm25_essential_topk_batch(block_docids, block_tfs,
                              flat_docids,   # int32 [TB*B] block layout
                              flat_tfs,      # float32 [TB*B]
                              sel_blocks,    # int32 [Q, NBe] essential
                              sel_weights,   # float32 [Q, NBe]
                              doc_lens, masks, mask_ids,
                              ne_start,      # int32 [Q, NE_SLOTS]
                              ne_len,        # int32 [Q, NE_SLOTS]
                              ne_idf,        # float32 [Q, NE_SLOTS]
                              ne_bound,      # float32 [Q] Σ maxc_ne
                              avg_len, k1: float, b: float, k: int):
    """Cohort launch → packed float32 [Q, 2k+1]:
    ``row = [values (k) | docids bitcast (k) | ok_flag bitcast (1)]``.
    ok=0 rows are UNCERTIFIED — the caller refires them on the full
    kernel (cold θ, boundary tie, or candidate overflow)."""
    def one(s, w, mid, ns, nl, ni, nb):
        live_col = jnp.take(masks, mid, axis=0)
        return _essential_one(block_docids, block_tfs, flat_docids,
                              flat_tfs, s, w, doc_lens, live_col,
                              ns, nl, ni, nb, avg_len, k1, b, k)

    vals, ids, ok = jax.vmap(one)(sel_blocks, sel_weights, mask_ids,
                                  ne_start, ne_len, ne_idf, ne_bound)
    ids_f = ids.astype(jnp.float32)
    ok_f = ok.astype(jnp.float32)
    return jnp.concatenate([vals, ids_f, ok_f[:, None]], axis=1)


# ---------------------------------------------------------------------------
# Dense-patch essential lane: the θ-warm fast lane for the DEGRADED
# tunnel regime (opportunistic on attached hardware — cohorts upgrade
# to it when every NE term has a dense row, else the binary lane
# below serves them).
#
# The binary-search patch phase above costs NE_SLOTS×21 DEPENDENT
# gathers over the 47M-lane flat postings — fine when a gather is ~µs
# on attached hardware, catastrophic in the tunnel's degraded mode
# where every dependent device op pays a sync (measured 862 ms/launch
# vs 151 ms for the plain nb-256 kernel at 2M docs). But the
# non-essential terms are BY CONSTRUCTION the high-df ones (MaxScore
# splits on max contribution ≈ ascending idf), so a dense [H, ND]
# tf table over the ~hundred hottest terms is small (f16, tf counts
# are exact integers < 2048) and turns the whole patch into ONE flat
# gather per NE slot: dense_tf[row*ND + cand_id]. Same certificate,
# same exactness contract, ~20 ops instead of ~170 dependent gathers.
# ---------------------------------------------------------------------------


def _essential_dense_one(block_docids, block_tfs, dense_tf, sel_blocks,
                         sel_weights, doc_lens, live_col,
                         ne_row, ne_idf, ne_bound,
                         avg_len, k1: float, b: float, k: int):
    dt = _score_dtype()
    nd = doc_lens.shape[0]
    cand_ids, ess, overflow_bound = _essential_phase1(
        block_docids, block_tfs, sel_blocks, sel_weights, doc_lens,
        live_col, ne_bound, avg_len, k1, b)

    # ---- phase 2: dense-table patch — one gather per NE slot
    safe_ids = jnp.clip(cand_ids, 0, nd - 1)
    cdl = jnp.take(doc_lens, safe_ids).astype(dt)
    cnorm = k1 * (1.0 - b + b * cdl / jnp.asarray(avg_len, dt))
    patched = jnp.where(jnp.isfinite(ess), ess,
                        jnp.asarray(-jnp.inf, dt))
    flat_dense = dense_tf.reshape(-1)
    # flat-index dtype: int64 only exists under x64; with x64 off the
    # BUILDER's h cap (search/fastpath.py _build_dense_hot) is the sole
    # guarantee that rows*docs stays under 2^31 — keep it if you touch
    # either side
    idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    for ti in range(NE_SLOTS):
        row = ne_row[ti]                       # -1 ⇒ slot unused
        srow = jnp.maximum(row, 0).astype(idt)
        idx = srow * nd + safe_ids.astype(idt)
        ptf = jnp.take(flat_dense, idx).astype(dt)
        ptf = jnp.where(row >= 0, ptf, 0.0)
        add = jnp.where(ptf > 0.0,
                        ne_idf[ti].astype(dt) * ptf / (ptf + cnorm),
                        0.0)
        patched = jnp.where(jnp.isfinite(patched), patched + add,
                            patched)

    return _essential_epilogue(patched, cand_ids, overflow_bound, k)


@tracked_jit(static_argnames=("k1", "b", "k"))
def bm25_essential_dense_topk_batch(block_docids, block_tfs,
                                    dense_tf,      # f16 [H, ND] hot-term tf
                                    sel_blocks,    # int32 [Q, NBe]
                                    sel_weights,   # rail [Q, NBe]
                                    doc_lens, masks, mask_ids,
                                    ne_row,        # int32 [Q, NE_SLOTS] row
                                    ne_idf,        # rail [Q, NE_SLOTS]
                                    ne_bound,      # rail [Q] Σ maxc_ne
                                    avg_len, k1: float, b: float, k: int):
    """θ-warm essential lane with the DENSE hot-term patch. Packing is
    the binary-search lane's: float32 [Q, 2k+1] =
    ``[values (k) | docids bitcast (k) | ok_flag bitcast (1)]``;
    ok=0 rows refire on the full kernel."""
    def one(s, w, mid, nr, ni, nb):
        live_col = jnp.take(masks, mid, axis=0)
        return _essential_dense_one(block_docids, block_tfs, dense_tf,
                                    s, w, doc_lens, live_col,
                                    nr, ni, nb, avg_len, k1, b, k)

    vals, ids, ok = jax.vmap(one)(sel_blocks, sel_weights, mask_ids,
                                  ne_row, ne_idf, ne_bound)
    ids_f = ids.astype(jnp.float32)
    ok_f = ok.astype(jnp.float32)
    return jnp.concatenate([vals, ids_f, ok_f[:, None]], axis=1)


# ---------------------------------------------------------------------------
# v2 serving kernel: merge-based f32 candidates + exact f64 re-rank.
#
# Phase A replaces the monolithic O(P·logP) lax.sort with the
# linear-work bitonic MERGE of per-term sorted runs (ops/merge.py,
# measured 3.0x on chip) and runs entirely in float32 — sound because
# phase A only nominates CANDIDATES. Phase B recomputes the top-C
# candidates' scores EXACTLY in float64 (per-term binary search in the
# flat postings — the essential-lane patch machinery generalized to all
# terms) and re-ranks by (float32 score desc, docid asc), the same
# contract as the v1 kernel. A device certificate proves no
# non-candidate can reach the top k: every excluded doc's f32 score is
# <= the (C+1)th candidate value, and the f32 pipeline's relative error
# vs f64 is bounded by _F32_SLACK; failures (mass score-ties wider than
# C — degenerate corpora) refire on the exact v1 kernel.
# ---------------------------------------------------------------------------

CAND_V2 = 4096      # candidates re-ranked exactly per query
MAX_T = 16          # term-instance slots for the re-rank binary search
# bound on the f32 phase-A pipeline's relative error vs exact f64:
# ~5 ops per contribution + a <=4-level doubling-scan sum of <=16
# positive terms keeps it well under 32*2^-24; 128*2^-24 adds margin
_F32_SLACK = 128.0 * 2.0 ** -24


def _stable_top_c(cand, mk, c):
    """[Q, P] -> (ids [Q, c], bound [Q]): the c candidates with docid-
    ascending tie order at the boundary (cand is docid-ordered so
    cumulative tie rank = docid rank), plus the (c+1)th value — the
    certificate's exclusion bound."""
    def one(cand_q, mk_q):
        _vals, ids, bound = _stable_topk(cand_q, mk_q, c,
                                         bound_slot=True)
        return ids, bound
    return jax.vmap(one)(cand, mk)


@tracked_jit(static_argnames=("n_slots", "k1", "b", "k"))
def bm25_topk_total_merge_batch(
        block_docids,   # int32 [TB, B]
        block_tfs,      # float32 [TB, B]
        sel_blocks,     # int32 [Q, NB] SLOTTED (term runs on slot
                        #   boundaries; slot = NB // n_slots blocks)
        sel_weights,    # rail-dtype [Q, NB]
        doc_lens,       # float32 [ND]
        masks,          # bool [F_SLOTS, ND]
        mask_ids,       # int32 [Q]
        avg_len, n_slots: int, k1: float, b: float, k: int):
    """The v1 exact kernel with ONE substitution: the monolithic
    O(P·logP) ``lax.sort`` becomes the linear-work bitonic merge of the
    per-term sorted runs (ops/merge.py), carrying the rail-dtype
    contributions through the merge. Everything downstream — doubling
    segmented scan, exact totals, stable lowest-docid top-k — is the v1
    code verbatim, so output equivalence is by construction (same
    packing: [values (k) | docids (k) | total], float32 [Q, 2k+1])."""
    from elasticsearch_tpu.ops.merge import merge_sorted_slots
    Q, NB = sel_blocks.shape
    B = block_docids.shape[1]
    P = NB * B
    L = P // n_slots
    dt = _score_dtype()

    def gather_one(s, w, mid):
        live_col = jnp.take(masks, mid, axis=0)
        d = jnp.take(block_docids, s, axis=0)
        tf = jnp.take(block_tfs, s, axis=0).astype(dt)
        dl = jnp.take(doc_lens, d).astype(dt)
        contrib = bm25_contrib(w.astype(dt), tf, dl,
                               jnp.asarray(avg_len, dt), k1, b)
        contrib = jnp.where((tf > 0.0) & jnp.take(live_col, d),
                            contrib, jnp.asarray(0.0, dt))
        key = jnp.where(tf > 0.0, d, _SENTINEL)
        return key.reshape(-1), contrib.reshape(-1)

    keys, cons = jax.vmap(gather_one)(sel_blocks, sel_weights, mask_ids)
    # the merge carries the LANE INDEX as payload (all-int32 — the
    # pallas chunk kernels must never see the rail dtype: Mosaic has no
    # real f64 and silently loses the rail's precision); the rail-dtype
    # contributions are gathered through the merged permutation at XLA
    # level, where f64 is exact
    lane = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None, :],
                            (Q, P)).reshape(Q, n_slots, L)
    mk, midx = merge_sorted_slots(keys.reshape(Q, n_slots, L), lane)
    x = jnp.take_along_axis(cons, midx, axis=1)
    # runs <= N_SLOTS=16 term instances: 4 steps cover them; the
    # default 5th would be a wasted full-width pass per launch
    x = _doubling_scan(mk, x, steps=(1, 2, 4, 8))
    cand, totals = _run_last_candidates(mk, x)

    def topk_one(cand_q, mk_q):
        vals, ids = _stable_topk(cand_q, mk_q, k)
        return vals.astype(jnp.float32), ids

    vals, ids = jax.vmap(topk_one)(cand, mk)
    ids_f = ids.astype(jnp.float32)
    tot_f = totals.astype(jnp.float32)
    return jnp.concatenate([vals, ids_f, tot_f[:, None]], axis=1)


@tracked_jit(static_argnames=("n_slots", "k1", "b", "k"))
def bm25_candidates_rerank_batch(
        block_docids,   # int32 [TB, B]
        block_tfs,      # float32 [TB, B]
        flat_docids,    # int32 [TB*B] block layout (re-rank search)
        flat_tfs,       # float32 [TB*B]
        sel_blocks,     # int32 [Q, NB] SLOTTED: each term-instance run
                        #   starts on a slot boundary (NB/n_slots blocks)
        sel_weights,    # float32 [Q, NB]
        doc_lens,       # float32 [ND]
        masks,          # bool [F_SLOTS, ND]
        mask_ids,       # int32 [Q]
        term_start,     # int32 [Q, MAX_T] flat posting offsets
        term_len,       # int32 [Q, MAX_T]
        term_idf,       # f64 (f32 when x64 off) [Q, MAX_T]
        avg_len,        # f64 scalar (f32 when x64 off)
        n_slots: int, k1: float, b: float, k: int):
    """Cohort launch → packed float32 [Q, 2k+2]:
    ``row = [values (k) | docids bitcast (k) | total bitcast |
    ok bitcast]``. ok=0 rows are UNCERTIFIED (score-tie mass wider than
    CAND_V2 at the boundary) — the caller refires them on the exact v1
    kernel."""
    from elasticsearch_tpu.ops.merge import merge_sorted_slots
    Q, NB = sel_blocks.shape
    B = block_docids.shape[1]
    P = NB * B
    L = P // n_slots
    nd = doc_lens.shape[0]
    dt = _score_dtype()
    avg32 = jnp.asarray(avg_len, jnp.float32)

    # ---- phase A: gather + f32 contributions, slot layout
    def gather_one(s, w, mid):
        live_col = jnp.take(masks, mid, axis=0)
        d = jnp.take(block_docids, s, axis=0)          # [NB, B]
        tf = jnp.take(block_tfs, s, axis=0)
        dl = jnp.take(doc_lens, d)
        norm = k1 * (1.0 - b + b * dl / avg32)
        contrib = w[:, None] * jnp.where(tf > 0.0, tf / (tf + norm),
                                         0.0)
        # filtered/dead docs keep their KEY (slot stays sorted) but
        # contribute 0 — the scan's x>0 drops them
        contrib = jnp.where(jnp.take(live_col, d), contrib, 0.0)
        key = jnp.where(tf > 0.0, d, _SENTINEL)
        return key.reshape(-1), contrib.reshape(-1)

    keys, cons = jax.vmap(gather_one)(sel_blocks, sel_weights, mask_ids)
    mk, mv = merge_sorted_slots(keys.reshape(Q, n_slots, L),
                                cons.reshape(Q, n_slots, L))

    # ---- segmented sums (runs <= MAX_T=16 instances: 4 steps)
    x = _doubling_scan(mk, mv, steps=(1, 2, 4, 8))
    cand, totals = _run_last_candidates(mk, x)
    cids, bound = _stable_top_c(cand, mk, CAND_V2)

    # ---- phase B: exact f64 re-rank of the candidates
    n_flat = flat_docids.shape[0]

    # halving steps resolving any per-term posting range: df <= ND, so
    # ceil(log2(ND))+1 steps always close the search (static in ND —
    # tiny test corpora compile ~11 steps, the 2M bench 22)
    n_steps = max(1, (nd - 1).bit_length()) + 1

    def rerank_one(cq, mid, ts, tl, ti):
        live_col = jnp.take(masks, mid, axis=0)
        safe = jnp.clip(cq, 0, nd - 1)
        dl = jnp.take(doc_lens, safe).astype(dt)
        cnorm = k1 * (1.0 - b + b * dl / jnp.asarray(avg_len, dt))
        score = jnp.zeros(CAND_V2, dt)
        for t in range(MAX_T):
            lo0 = ts[t]
            ln = tl[t]
            lo = jnp.full((CAND_V2,), lo0, jnp.int32)
            hi = jnp.full((CAND_V2,), lo0 + ln, jnp.int32)
            for _ in range(n_steps):
                mid_ = (lo + hi) // 2
                vdoc = jnp.take(flat_docids,
                                jnp.clip(mid_, 0, n_flat - 1))
                go_right = vdoc < cq
                lo = jnp.where(go_right, mid_ + 1, lo)
                hi = jnp.where(go_right, hi, mid_)
            in_range = (lo < lo0 + ln) & (ln > 0)
            at = jnp.clip(lo, 0, n_flat - 1)
            found = in_range & (jnp.take(flat_docids, at) == cq)
            ptf = jnp.where(found, jnp.take(flat_tfs, at).astype(dt),
                            0.0)
            score = score + jnp.where(
                ptf > 0.0, ti[t].astype(dt) * ptf / (ptf + cnorm), 0.0)
        valid = (cq != _SENTINEL) & jnp.take(live_col, safe) \
            & (score > 0.0)
        score = jnp.where(valid, score, jnp.asarray(-jnp.inf, dt))
        disp = score.astype(jnp.float32)
        neg = jnp.where(jnp.isfinite(disp), -disp,
                        jnp.asarray(jnp.inf, jnp.float32))
        tie = jnp.where(jnp.isfinite(disp), cq, _SENTINEL)
        _n, sids, svals, sdt = jax.lax.sort(
            (neg, tie, disp, score), num_keys=2)
        out_vals = svals[:k]
        out_ids = jnp.where(jnp.isfinite(out_vals), sids[:k],
                            _SENTINEL)
        kth = jnp.min(jnp.where(jnp.isfinite(out_vals), sdt[:k],
                                jnp.asarray(jnp.inf, dt)))
        kth = jnp.where(jnp.isfinite(out_vals[k - 1]), kth,
                        jnp.asarray(-jnp.inf, dt))
        return out_vals, out_ids, kth

    vals, ids, kth = jax.vmap(rerank_one)(cids, mask_ids, term_start,
                                          term_len, term_idf)

    # certificate: every excluded doc's true score <= bound*(1+slack);
    # also trivially certified when fewer than C+1 docs matched, or
    # when the result has fewer than k hits (then ALL matches are
    # candidates and bound is -inf)
    bound_up = jnp.where(jnp.isfinite(bound),
                         bound.astype(dt) * (1.0 + _F32_SLACK),
                         jnp.asarray(-jnp.inf, dt))
    ok = (bound_up < kth) | ~jnp.isfinite(bound)
    ids_f = ids.astype(jnp.float32)
    tot_f = totals.astype(jnp.float32)
    ok_f = ok.astype(jnp.float32)
    return jnp.concatenate([vals, ids_f, tot_f[:, None], ok_f[:, None]],
                           axis=1)


@tracked_jit(static_argnames=("k1", "b", "k"))
def bm25_topk_total_batch(block_docids,   # int32 [TB, B]
                          block_tfs,      # float32 [TB, B]
                          sel_blocks,     # int32 [Q, NB]
                          sel_weights,    # float32 [Q, NB]
                          doc_lens,       # float32 [ND]
                          masks,          # bool [F_SLOTS, ND]
                          mask_ids,       # int32 [Q] row into masks
                          avg_len, k1: float, b: float, k: int):
    """Cohort launch → ONE packed float32 [Q, 2k+1]:
    ``row = [values (k) | docids bitcast to f32 (k) | total bitcast (1)]``.
    Ints ride as float CASTS (exact < 2^24; the axon runtime
    miscompiles multi-bitcast concats — see ops/plan.pack_result)."""
    def one(s, w, mid):
        live_col = jnp.take(masks, mid, axis=0)
        return _topk_total(block_docids, block_tfs, s, w, doc_lens,
                           live_col, avg_len, k1, b, k)

    vals, ids, totals = jax.vmap(one)(sel_blocks, sel_weights, mask_ids)
    ids_f = ids.astype(jnp.float32)
    tot_f = totals.astype(jnp.float32)
    return jnp.concatenate([vals, ids_f, tot_f[:, None]], axis=1)
