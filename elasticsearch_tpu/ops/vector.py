"""Dense-vector scoring kernels — brute-force kNN as MXU matmuls.

The TPU replacement for the reference's script-based brute force (ref:
x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:112-170 —
cosineSimilarity/dotProduct/l2norm iterate doc-values bytes per doc; no
ANN exists at this version, SURVEY.md §2.6 "vectors"). Here the whole
segment's vectors live in HBM as an [ND, D] slab (bf16 by default) and a
query batch scores as one [Q, D] @ [D, ND] matmul with f32 accumulation —
exactly the shape the MXU wants.

Cosine is computed as dot over pre-normalized doc vectors (norms applied
at upload), matching float32 cosine to ~1e-3; set dtype=float32 for exact
parity at 2× HBM.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.telemetry.engine import tracked_jit


def prepare_vectors(vectors: np.ndarray, similarity: str,
                    dtype=jnp.bfloat16):
    """Host-side prep for device upload: returns (prepped [ND, D], norms
    [ND]). For cosine the slab is pre-normalized (zero vectors stay zero)."""
    norms = np.linalg.norm(vectors, axis=1)
    if similarity == "cosine":
        safe = np.where(norms > 0, norms, 1.0)[:, None]
        prepped = (vectors / safe).astype(dtype)
    else:
        prepped = vectors.astype(dtype)
    return prepped, norms.astype(np.float32)


@tracked_jit
def dot_scores(queries: jax.Array,   # [Q, D] float32
               vectors: jax.Array    # [ND, D] (bf16 or f32)
               ) -> jax.Array:       # [Q, ND] float32
    # HIGHEST keeps f32 slabs exact (parity checks); bf16 slabs are
    # unaffected — single-pass MXU either way
    return jnp.einsum("qd,nd->qn", queries.astype(vectors.dtype), vectors,
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)


@tracked_jit
def cosine_scores(queries: jax.Array,  # [Q, D] float32 (un-normalized)
                  unit_vectors: jax.Array  # [ND, D] pre-normalized slab
                  ) -> jax.Array:
    qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
    q = queries / jnp.where(qn > 0, qn, 1.0)
    return dot_scores(q, unit_vectors)


@tracked_jit
def l2_scores(queries: jax.Array, vectors: jax.Array,
              doc_sq_norms: jax.Array  # [ND] float32 = ||v||²
              ) -> jax.Array:
    """Negated squared L2 distance (higher = closer), via the
    ||q||² - 2q·v + ||v||² expansion so the matmul still rides the MXU."""
    dots = dot_scores(queries, vectors)                       # [Q, ND]
    q_sq = jnp.sum(queries * queries, axis=1, keepdims=True)  # [Q, 1]
    return -(q_sq - 2.0 * dots + doc_sq_norms[None, :])


def exact_rerank_scores(cand: np.ndarray, q32: np.ndarray,
                        similarity: str) -> np.ndarray:
    """Host exact-f32 re-rank formulas (ES score transforms included) —
    the ONE implementation shared by KnnQuery._exact_rerank (per-shard
    loop) and the mesh kNN path (parallel/mesh_executor.py), so the two
    serving paths cannot drift: quantized slabs NOMINATE on device,
    then the top candidates re-score here in exact float32."""
    cand = cand.astype(np.float32)
    if similarity == "cosine":
        nrm = np.linalg.norm(cand, axis=1) * np.linalg.norm(q32)
        sim = cand @ q32 / np.where(nrm > 0, nrm, 1.0)
        return ((1.0 + sim) / 2.0).astype(np.float32)
    if similarity == "dot_product":
        return ((1.0 + cand @ q32) / 2.0).astype(np.float32)
    d2 = ((cand - q32[None, :]) ** 2).sum(axis=1)
    return (1.0 / (1.0 + d2)).astype(np.float32)


# ---------------------------------------------------------------------------
# Scalar references (parity targets for the painless functions in the
# reference: cosineSimilarity / dotProduct / l2norm)
# ---------------------------------------------------------------------------

def cosine_reference(query: np.ndarray, vectors: np.ndarray) -> np.ndarray:
    qn = np.linalg.norm(query)
    vn = np.linalg.norm(vectors, axis=1)
    denom = np.where((qn > 0) & (vn > 0), qn * vn, 1.0)
    return (vectors @ query) / denom


def dot_reference(query: np.ndarray, vectors: np.ndarray) -> np.ndarray:
    return vectors @ query


def l2_reference(query: np.ndarray, vectors: np.ndarray) -> np.ndarray:
    return -np.sum((vectors - query[None, :]) ** 2, axis=1)


# ---------------------------------------------------------------------------
# Batched kNN nomination: the serving-cohort kernel
# ---------------------------------------------------------------------------

@tracked_jit(static_argnames=("similarity", "cut"))
def knn_nominate_batch(queries: jax.Array,      # [Q, D] float32
                       vectors: jax.Array,      # [ND, D] slab (bf16/f32)
                       sq_norms: jax.Array,     # [ND] float32 ||v||²
                       has_value: jax.Array,    # [ND] bool
                       live: jax.Array,         # [ND] bool (deletes)
                       similarity: str, cut: int):
    """One launch for a COHORT of kNN queries: similarity matmul (MXU),
    ES score transform (cosine/dot → (1+raw)/2, l2 → 1/(1+d²)), missing
    mask, and per-row top-``cut``. Returns ([Q, cut] scores f32,
    [Q, cut] docids i32). The serving layer coalesces concurrent knn
    branches into this instead of one matvec chain per request — the
    whole cohort pays ONE degraded-launch round trip (the knn analogue
    of ops/plan.plan_topk_batch)."""
    if similarity == "cosine":
        raw = cosine_scores(queries, vectors)
        scores = (1.0 + raw) / 2.0
    elif similarity == "dot_product":
        raw = dot_scores(queries, vectors)
        scores = (1.0 + raw) / 2.0
    else:
        neg_sq = l2_scores(queries, vectors, sq_norms)
        scores = 1.0 / (1.0 - neg_sq)
    scores = jnp.where((has_value & live)[None, :], scores, -jnp.inf)
    top_s, top_i = jax.lax.top_k(scores, cut)
    return top_s.astype(jnp.float32), top_i.astype(jnp.int32)
