"""Batched BM25 scoring kernels.

The TPU replacement for the Lucene BulkScorer hot loop (ref:
search/internal/ContextIndexSearcher.java:210-213 — per-segment
``BulkScorer.score(leafCollector, liveDocs)``). Where Lucene iterates
postings one docid at a time with skip lists, these kernels score *all*
selected postings blocks in one launch:

    gather blocks → per-posting BM25 contribution → scatter-add into a
    dense per-doc score accumulator → (top-k in ops/topk.py)

Padding discipline (set up by index/segment.py): padded lanes carry
``tf = 0`` so their contribution is exactly 0, and padded *blocks* point at
a reserved all-zeros block appended at device upload, with weight 0 — no
masks needed anywhere in the hot path.

The BM25 formula matches Lucene 8's BM25Similarity (ref: Lucene
BM25Similarity.java — the (k1+1) numerator constant is dropped, which does
not change ranking):

    idf(t)  = ln(1 + (N - df + 0.5) / (df + 0.5))
    score   = idf * tf / (tf + k1 * (1 - b + b * dl / avgdl))

Lucene quantizes dl into a 1-byte norm (SmallFloat); we keep exact float
lengths — rankings agree at matched recall, absolute scores differ slightly
(SURVEY.md §7 "Scoring parity").

Compile observability: nothing here is jitted at module level — callers
either execute these eagerly (the dense fallback) or close over them in
their own jit (bench.py, ops/plan.py), so their per-shape compiles are
attributed to the CALLING kernel's entry in the compile tracker
(telemetry/engine.py); see `GET /_kernels`.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def idf(doc_freq, doc_count) -> float:
    """Lucene BM25 idf (BM25Similarity.idf)."""
    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


def bm25_contrib(sel_weights: jax.Array, tf: jax.Array, dl: jax.Array,
                 avg_len, k1: float, b: float) -> jax.Array:
    """Per-posting BM25 contribution [NB, B] — THE scoring expression
    (one definition; the dense path, the sorted-top-k path, and the
    Pallas kernel's reference all share it). The tf>0 guard protects the
    padding lanes from 0/0 NaNs."""
    norm = k1 * (1.0 - b + b * dl / avg_len)
    return sel_weights[:, None] * jnp.where(tf > 0.0, tf / (tf + norm), 0.0)


def bm25_block_scores(block_docids: jax.Array,   # int32 [TB, B] all blocks
                      block_tfs: jax.Array,      # float32 [TB, B]
                      sel_blocks: jax.Array,     # int32 [NB] selected block ids
                      sel_weights: jax.Array,    # float32 [NB] idf of owning term
                      doc_lens: jax.Array,       # float32 [ND]
                      avg_len: jax.Array,        # float32 scalar
                      k1: float, b: float) -> jax.Array:
    """Dense per-doc BM25 scores [ND] for the selected blocks.

    A doc's score is the sum over query terms of idf·tf/(tf+norm); docs
    matching no term end at exactly 0.0 (idf > 0 always, so any match
    scores > 0 — "matched" is recoverable from score > 0).
    """
    d = jnp.take(block_docids, sel_blocks, axis=0)        # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)          # [NB, B]
    dl = jnp.take(doc_lens, d)                            # [NB, B]
    contrib = bm25_contrib(sel_weights, tf, dl, avg_len, k1, b)
    scores = jnp.zeros(doc_lens.shape[0], jnp.float32)
    return scores.at[d.reshape(-1)].add(
        contrib.reshape(-1), mode="drop", unique_indices=False)


def match_mask(block_docids: jax.Array, block_tfs: jax.Array,
               sel_blocks: jax.Array, n_docs: int) -> jax.Array:
    """bool [ND]: docs appearing in ANY selected block (term/terms filters —
    the device analogue of a Lucene TermQuery bitset)."""
    d = jnp.take(block_docids, sel_blocks, axis=0)
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    mask = jnp.zeros(n_docs, jnp.bool_)
    return mask.at[d.reshape(-1)].max(tf.reshape(-1) > 0, mode="drop")


def match_count(block_docids: jax.Array, block_tfs: jax.Array,
                sel_blocks: jax.Array, clause_ids: jax.Array,
                n_clauses: int, n_docs: int) -> jax.Array:
    """int32 [ND]: number of distinct clauses each doc matches.

    Used for bool `must`/`minimum_should_match` semantics: each selected
    block carries the id of its owning clause; per-doc presence is computed
    per clause (scatter-max into a [ND, n_clauses] plane), then summed.
    n_clauses is static and small.
    """
    d = jnp.take(block_docids, sel_blocks, axis=0)        # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    present = jnp.zeros((n_docs, n_clauses), jnp.bool_)
    cid = jnp.broadcast_to(clause_ids[:, None], d.shape)  # [NB, B]
    present = present.at[d.reshape(-1), cid.reshape(-1)].max(
        tf.reshape(-1) > 0, mode="drop")
    return present.sum(axis=1, dtype=jnp.int32)


def block_max_scores(block_max_tf: jax.Array,   # float32 [TB]
                     block_min_len: jax.Array,  # float32 [TB]
                     sel_blocks: jax.Array,     # int32 [NB]
                     sel_weights: jax.Array,    # float32 [NB]
                     avg_len: jax.Array, k1: float, b: float) -> jax.Array:
    """Upper-bound score per selected block — the block-max WAND bound
    (ref: Lucene block-max impacts, TopDocsCollectorContext.java:210-217).
    Monotonic ↑ in tf, ↓ in dl ⇒ (max_tf, min_len) gives an exact bound."""
    mtf = jnp.take(block_max_tf, sel_blocks)
    mln = jnp.take(block_min_len, sel_blocks)
    norm = k1 * (1.0 - b + b * mln / avg_len)
    return sel_weights * (mtf / (mtf + norm))


# Python int literal, NOT jnp.int32(...): a module-level device scalar
# would be captured as a constant buffer by every jit using it, and on the
# axon backend any executable with a captured device buffer degrades ALL
# subsequent launches in the process to ~70ms (measured). Literals embed
# as immediates and are safe.
#
# Related axon-tunnel quirk (measured, see bench.py): ANY device→host
# readback (np.asarray / jax.device_get / scalar .item()) permanently
# flips the process into the same ~100ms-per-launch mode —
# block_until_ready alone does not. Benchmarks must do all timing before
# the first readback; serving paths amortize it by batching many queries
# per launch (the continuous-batching design, SURVEY.md §7 hard part 5).
# Real TPU runtimes (non-tunneled) do not behave this way.
_SENTINEL = 0x7FFFFFFF


def scan_run_bound(n_terms: int, floor: int = 32) -> int:
    """Static ``max_run`` for the doubling segmented scans: the smallest
    power of two ≥ max(n_terms, floor). The scan's coverage window equals
    this bound (steps 1..bound/2 sum a run of exactly ``bound``), and
    rounding to a power of two caps the number of compiled variants."""
    r = floor
    while r < n_terms:
        r *= 2
    return r


def segmented_topk(keys: jax.Array, contribs: jax.Array, k: int,
                   sentinel, max_run: int = 32):
    """Top-k of per-key contribution sums WITHOUT a dense accumulator:
    sort (key, contrib) pairs by key, segmented-sum each key-run with a
    DOUBLING scan (Hillis-Steele with the key-equality carry — valid
    because runs are contiguous after the sort), then top-k over run
    totals at run-last positions.

    The doubling scan — not a global cumsum — is a PRECISION contract:
    a float32 prefix over 500K postings carries absolute error ~
    prefix·2^-24, which reorders top-k boundary docs (measured recall
    0.997 vs an exact scorer); summing each run's ≤``max_run`` elements
    directly keeps full f32 accuracy. ``max_run`` must bound the
    longest real run (per-doc entries ≤ query terms here; sentinel runs
    are longer but never read).

    Keys equal to `sentinel` (padding) sort last and never win. Returns
    (values [k], keys [k]); empty slots are (-inf, sentinel)."""
    sorted_k, sorted_c = jax.lax.sort((keys, contribs), num_keys=1)
    x = sorted_c
    step = 1
    while step < min(max_run, keys.shape[0]):
        prev_x = jnp.pad(x[:-step], (step, 0))
        prev_k = jnp.pad(sorted_k[:-step], (step, 0),
                         constant_values=-1)
        x = x + jnp.where(prev_k == sorted_k, prev_x, 0.0)
        step *= 2
    nxt = jnp.concatenate([sorted_k[1:],
                           jnp.full(1, -1, sorted_k.dtype)])
    is_last = sorted_k != nxt
    cand = jnp.where(is_last & (x > 0.0) & (sorted_k != sentinel),
                     x, -jnp.inf)
    vals, pos = jax.lax.top_k(cand, k)
    ids = jnp.take(sorted_k, pos)
    ids = jnp.where(jnp.isfinite(vals), ids, sentinel)
    return vals, ids


def bm25_sorted_topk(block_docids: jax.Array,   # int32 [TB, B]
                     block_tfs: jax.Array,      # float32 [TB, B]
                     sel_blocks: jax.Array,     # int32 [NB]
                     sel_weights: jax.Array,    # float32 [NB]
                     doc_lens: jax.Array,       # float32 [ND]
                     live: jax.Array,           # bool [ND]
                     avg_len: jax.Array, k1: float, b: float, k: int,
                     max_run: int = 32):
    """BM25 top-k WITHOUT a dense score accumulator — the TPU-native hot
    path. XLA scatter on TPU serializes updates (measured ~70ms for 8K
    postings), so instead of scattering into scores[ND] this kernel:

      1. gathers the selected postings blocks (gathers vectorize fine),
      2. sorts (docid, contribution) pairs by docid (`lax.sort` — bitonic
         on the VPU),
      3. sums each docid-run with a cumsum + run-boundary subtraction
         (the segmented-reduction trick: exclusive prefix at run start is
         propagated by cummax since prefixes are non-decreasing),
      4. top-k over run totals at run-last positions.

    Cost is O(P log P) in the number of query postings P — independent of
    corpus size, like Lucene's postings iteration, but batched and
    branch-free. Returns (values [k], docids [k]); empty slots are
    (-inf, sentinel).
    """
    d = jnp.take(block_docids, sel_blocks, axis=0)       # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    dl = jnp.take(doc_lens, d)
    contrib = bm25_contrib(sel_weights, tf, dl, avg_len, k1, b)

    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = tf.reshape(-1) > 0.0
    # padding sorts to the end; deleted docs contribute 0 and are dropped
    # by the totals>0 mask
    dkey = jnp.where(valid, dflat, _SENTINEL)
    cflat = jnp.where(valid & jnp.take(live, dflat), cflat, 0.0)
    # max_run MUST bound the per-doc term-instance count — callers with
    # unbounded term lists pass scan_run_bound(n_terms) (a 31+-term
    # query under the fixed 32 default silently drops contributions)
    return segmented_topk(dkey, cflat, k, _SENTINEL, max_run=max_run)


# ---------------------------------------------------------------------------
# Scalar reference (the "AbstractQueryTestCase" analogue: kernels are
# property-tested against this, SURVEY.md §4 lesson)
# ---------------------------------------------------------------------------

def bm25_reference_scores(postings_per_term, idfs, doc_lens, avg_len,
                          k1: float, b: float) -> np.ndarray:
    """Pure-numpy scalar BM25: postings_per_term is a list of (docids, tfs)
    arrays, one per query term, idfs the matching idf list."""
    scores = np.zeros(len(doc_lens), np.float64)
    for (docids, tfs), w in zip(postings_per_term, idfs):
        for d, tf in zip(docids, tfs):
            dl = doc_lens[d]
            scores[d] += w * tf / (tf + k1 * (1 - b + b * dl / avg_len))
    return scores


def bm25_sorted_topk_batch(block_docids: jax.Array,   # int32 [TB, B]
                           block_tfs: jax.Array,      # float32 [TB, B]
                           sel_blocks: jax.Array,     # int32 [Q, NB]
                           sel_weights: jax.Array,    # float32 [Q, NB]
                           doc_lens: jax.Array,       # float32 [ND]
                           live: jax.Array,           # bool [ND]
                           avg_len, k1: float, b: float, k: int,
                           max_run: int = 32):
    """Many queries per launch: vmap of bm25_sorted_topk over a [Q, NB]
    selection batch → ([Q, k] values, [Q, k] docids).

    This is the continuous-batching serving shape (SURVEY.md §7 hard
    part 5): launch overhead — pathological under the axon tunnel's
    post-readback ~100ms mode, but real on any runtime — amortizes over
    Q queries, and the per-query sorts batch onto the VPU. Queries with
    fewer postings pad their selection with the reserved zero block."""
    return jax.vmap(
        lambda s, w: bm25_sorted_topk(block_docids, block_tfs, s, w,
                                      doc_lens, live, avg_len, k1, b, k,
                                      max_run=max_run)
    )(sel_blocks, sel_weights)
