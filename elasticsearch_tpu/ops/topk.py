"""On-device top-k selection and merge.

Replaces Lucene's TopScoreDocCollector + the coordinator's TopDocs.merge
(ref: search/query/TopDocsCollectorContext.java, action/search/
SearchPhaseController.java:154-218). Exact top-k via lax.top_k; a TPU
approximate variant via lax.approx_max_k (recall-targeted, MIPS-style
partial reduction) for latency-critical paths; and a pairwise merge used
both host-side across segments and inside collectives across shards.

Tie-breaking matches Lucene: equal scores order by ascending docid.
lax.top_k already returns the smallest index among equals, so per-segment
results agree with the reference; the merge re-sorts by (-score, docid).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from elasticsearch_tpu.telemetry.engine import tracked_jit


@tracked_jit(static_argnames=("k",))
def topk(scores: jax.Array, k: int):
    """Exact (values, indices) top-k, descending; ties → ascending index."""
    return jax.lax.top_k(scores, k)


@tracked_jit(static_argnames=("k", "recall_target"))
def approx_topk(scores: jax.Array, k: int, recall_target: float = 0.95):
    """TPU-optimized approximate top-k (lax.approx_max_k): ~constant-factor
    faster at large n; recall_target trades speed for exactness."""
    return jax.lax.approx_max_k(scores, k, recall_target=recall_target)


@tracked_jit(static_argnames=("k",))
def masked_topk(scores: jax.Array, mask: jax.Array, k: int):
    """Top-k over masked docs only. The caller supplies the full mask
    (matched & live & not-padding — filter-only queries legitimately score
    0.0, so matching is NOT inferred from score). Masked-out docs drop to
    -inf; a returned value of -inf means "fewer than k matches"."""
    masked = jnp.where(mask, scores, -jnp.inf)
    return jax.lax.top_k(masked, k)


@tracked_jit(static_argnames=("k",))
def merge_topk(values_a: jax.Array, ids_a: jax.Array,
               values_b: jax.Array, ids_b: jax.Array, k: int):
    """Merge two top-k lists into one, re-tie-breaking by ascending id.

    Sort key packs (-score, id) lexicographically via sort over negated
    score with a stable secondary sort on id (jnp.lexsort semantics).
    """
    v = jnp.concatenate([values_a, values_b])
    i = jnp.concatenate([ids_a, ids_b])
    # primary: score desc; secondary: id asc. lax.sort is stable, so sort
    # by id first, then by negated score.
    order_id = jnp.argsort(i, stable=True)
    v2, i2 = v[order_id], i[order_id]
    order_s = jnp.argsort(-v2, stable=True)
    v3, i3 = v2[order_s], i2[order_s]
    return v3[:k], i3[:k]
