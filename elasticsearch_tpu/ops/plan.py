"""Fused query-plan top-k kernel — the serving-path hot loop.

This is the TPU replacement for Lucene's BooleanQuery/ConjunctionDISI
scoring stack (ref: search/internal/ContextIndexSearcher.java:196-232 —
per-segment ``BulkScorer.score``; BooleanWeight/ConjunctionDISI iterator
trees). Instead of executing each clause into a dense [ND] score/mask pair
via scatter (XLA scatter-add serializes on TPU — measured ~70ms/launch,
see ops/bm25.py), the whole boolean tree executes as ONE sorted
segmented-reduction program over the query's postings:

  1. gather the selected postings blocks of every scoring/filtering clause
     (gathers vectorize), tagging each posting with (group, subgroup) ids —
     a "group" is one bool clause (a match query, a term filter, …), a
     "subgroup" one term within it;
  2. sort (docid, group, subgroup, contribution) lexicographically
     (`lax.sort` — bitonic on the VPU);
  3. segmented reductions over the sorted runs compute, per (doc, group):
     distinct-subgroup counts (minimum_should_match / operator=and inside a
     clause) and summed BM25 contributions; then per doc: which groups are
     present, must/filter/should satisfaction, must_not exclusion, and the
     combined score (sum or dis-max);
  4. dense, vectorized column predicates (range/exists/numeric-term — no
     scatter anywhere in their construction) enter as one gathered
     ``dense_mask`` lookup;
  5. `lax.top_k` over the per-doc run totals yields (scores, docids) and an
     exact matching-doc count, with NO dense [ND] accumulator in the path.

Cost is O(P log P) in the query's postings count P — corpus-size
independent, like Lucene's skip-list iteration, but branch-free and
batchable (vmap over queries = continuous batching, SURVEY.md §7 hard
part 5).

Group kinds mirror the bool query's occur classes (ref:
BoolQueryBuilder / Lucene BooleanClause.Occur).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.telemetry.engine import tracked_jit

MUST = 0
SHOULD = 1
FILTER = 2
MUST_NOT = 3

_SENTINEL = 0x7FFFFFFF  # padding docid; sorts after every real docid

# float32 represents every integer < 2^24 exactly — the ceiling for ids
# that ride packed readbacks as float casts (pack_result). Segment doc
# counts sit far below it; the mesh path's GLOBAL ids (shard * nd_padded
# + docid) can approach it at many-shard scale and must fall back to the
# per-shard RPC merge instead of silently losing low bits.
PACKED_ID_LIMIT = 1 << 24


def check_packed_id_limit(nd: int, where: str) -> None:
    """Enforce the ``nd < 2^24`` float-pack invariant loudly at build /
    register time (a violation later would corrupt docids silently)."""
    if nd >= PACKED_ID_LIMIT:
        raise ValueError(
            f"{where}: {nd} docs (padded) >= 2^24 — float32-packed "
            f"readback ids would lose precision; shard the corpus "
            f"further (ops/plan.py pack_result invariant)")


class FieldStream(NamedTuple):
    """One field's postings selection for a query plan.

    Device-resident corpus arrays plus the per-query selection: block ids
    and, per selected block, the owning (group, subgroup), the scoring
    weight (idf·boost), and whether the clause scores constant-per-match
    (keyword term semantics: Lucene keyword fields index no norms, score =
    idf·tf/(tf+k1) with tf=1) instead of full BM25.
    """

    block_docids: jax.Array   # int32 [TB+1, B] (with reserved zero block)
    block_tfs: jax.Array      # float32 [TB+1, B]
    doc_lens: jax.Array       # float32 [ND]
    avg_len: jax.Array        # float32 scalar (shard-level stat)
    sel_blocks: jax.Array     # int32 [NB]
    sel_group: jax.Array      # int32 [NB]
    sel_sub: jax.Array        # int32 [NB]
    sel_weight: jax.Array     # float32 [NB]
    sel_const: jax.Array      # bool [NB] — constant-score contribution


def _prev(x: jax.Array, fill) -> jax.Array:
    return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])


def _segsum(x: jax.Array, is_start: jax.Array) -> jax.Array:
    """Inclusive prefix sums within runs delimited by ``is_start``.

    Requires x >= 0 (exclusive prefixes are then non-decreasing, so the
    run-start exclusive prefix propagates forward by cummax)."""
    cs = jnp.cumsum(x)
    excl = cs - x
    start = jax.lax.cummax(jnp.where(is_start, excl, jnp.zeros_like(excl)))
    return cs - start


def _segmax(x: jax.Array, is_start: jax.Array) -> jax.Array:
    """Inclusive prefix max within runs (associative segmented-max scan)."""

    def comb(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, jnp.maximum(va, vb))

    _, out = jax.lax.associative_scan(comb, (is_start, x))
    return out


def plan_topk_body(streams: Tuple[FieldStream, ...],
                   group_kind: jax.Array,    # int32 [G]
                   group_req: jax.Array,     # int32 [G]
                   group_const: jax.Array,   # float32 [G]; NaN = sum contribs
                   live: jax.Array,          # bool [ND]
                   dense_mask: jax.Array,    # bool [ND] (all-true if unused)
                   n_must: jax.Array, n_filter: jax.Array, msm: jax.Array,
                   bonus: jax.Array, tie: jax.Array,
                   after_score: jax.Array,   # float32; _score search_after
                   k1: float, b: float, k: int, combine: str,
                   with_dense: bool, with_after: bool = False,
                   script_fn=None):
    """The kernel body, un-jitted: also called from inside shard_map
    (parallel/mesh_executor.py) where the surrounding SPMD program owns
    the jit.

    ``script_fn(score, docids) -> score`` is the script_score transform
    (a stable per-(segment, script) closure over device columns —
    search/plan.py binds it): applied to the combined per-doc score
    before top-k, so expression script_score queries ride this batched
    kernel instead of the per-request dense path (BASELINE config 3
    through the product path)."""
    parts_d, parts_tf, parts_c, parts_g, parts_s = [], [], [], [], []
    for st in streams:
        d = jnp.take(st.block_docids, st.sel_blocks, axis=0)    # [NB, B]
        tf = jnp.take(st.block_tfs, st.sel_blocks, axis=0)
        dl = jnp.take(st.doc_lens, d)
        norm = k1 * (1.0 - b + b * dl / st.avg_len)
        hit = tf > 0.0
        bm25 = st.sel_weight[:, None] * jnp.where(hit, tf / (tf + norm), 0.0)
        contrib = jnp.where(st.sel_const[:, None],
                            jnp.where(hit, st.sel_weight[:, None], 0.0), bm25)
        parts_d.append(d.reshape(-1))
        parts_tf.append(tf.reshape(-1))
        parts_c.append(contrib.reshape(-1))
        parts_g.append(jnp.broadcast_to(
            st.sel_group[:, None], d.shape).reshape(-1))
        parts_s.append(jnp.broadcast_to(
            st.sel_sub[:, None], d.shape).reshape(-1))

    d_all = jnp.concatenate(parts_d)
    tf_all = jnp.concatenate(parts_tf)
    c_all = jnp.concatenate(parts_c)
    g_all = jnp.concatenate(parts_g)
    s_all = jnp.concatenate(parts_s)

    nd = live.shape[0]
    valid = (tf_all > 0.0) & jnp.take(live, jnp.clip(d_all, 0, nd - 1))
    dkey = jnp.where(valid, d_all, _SENTINEL)
    c_all = jnp.where(valid, c_all, 0.0)

    dkey, g, s, c = jax.lax.sort((dkey, g_all, s_all, c_all), num_keys=3)

    new_doc = dkey != _prev(dkey, -1)
    new_grp = new_doc | (g != _prev(g, -1))
    new_sub = new_grp | (s != _prev(s, -1))
    is_grp_last = jnp.concatenate([new_grp[1:], jnp.ones(1, bool)])
    is_doc_last = jnp.concatenate([new_doc[1:], jnp.ones(1, bool)])

    # per-(doc, group): distinct subgroups matched + summed contribution
    sub_cnt = _segsum(new_sub.astype(jnp.float32), new_grp)
    grp_score = _segsum(c, new_grp)

    ng = group_kind.shape[0]
    gc = jnp.clip(g, 0, ng - 1)
    kind = jnp.take(group_kind, gc)
    req = jnp.take(group_req, gc)
    cval = jnp.take(group_const, gc)
    present = is_grp_last & (sub_cnt >= req.astype(jnp.float32))
    gscore = jnp.where(jnp.isnan(cval), grp_score, cval)
    scoring = (kind == MUST) | (kind == SHOULD)

    score_in = jnp.where(present & scoring, gscore, 0.0)
    must_in = (present & (kind == MUST)).astype(jnp.float32)
    filt_in = (present & (kind == FILTER)).astype(jnp.float32)
    should_in = (present & (kind == SHOULD)).astype(jnp.float32)
    mnot_in = (present & (kind == MUST_NOT)).astype(jnp.float32)

    doc_score = _segsum(score_in, new_doc)
    doc_must = _segsum(must_in, new_doc)
    doc_filt = _segsum(filt_in, new_doc)
    doc_should = _segsum(should_in, new_doc)
    doc_mnot = _segsum(mnot_in, new_doc)

    if combine == "dismax":
        mx_in = jnp.where(present & scoring, gscore, -jnp.inf)
        doc_max = _segmax(mx_in, new_doc)
        score = jnp.where(jnp.isfinite(doc_max),
                          doc_max + tie * (doc_score - doc_max), 0.0)
    else:
        score = doc_score
    score = score + bonus
    if script_fn is not None:
        score = jnp.asarray(
            script_fn(score, jnp.clip(dkey, 0, nd - 1)), score.dtype)

    passed = (is_doc_last & (dkey != _SENTINEL)
              & (doc_must >= n_must.astype(jnp.float32))
              & (doc_filt >= n_filter.astype(jnp.float32))
              & (doc_should >= msm.astype(jnp.float32))
              & (doc_mnot == 0.0))
    if with_dense:
        passed = passed & jnp.take(dense_mask, jnp.clip(dkey, 0, nd - 1))
    if with_after:
        # search_after on _score: strictly-after the cursor; ties excluded
        # (as in the dense executor — reliable tie paging needs a trailing
        # _doc key, which implies a sort spec and the dense path)
        passed = passed & (score < after_score)

    cand = jnp.where(passed, score, -jnp.inf)
    if k > cand.shape[0]:
        pad = k - cand.shape[0]
        cand = jnp.concatenate([cand, jnp.full(pad, -jnp.inf)])
        dkey = jnp.concatenate(
            [dkey, jnp.full(pad, _SENTINEL, dkey.dtype)])
    vals, pos = jax.lax.top_k(cand, k)
    ids = jnp.take(dkey, pos)
    ids = jnp.where(vals > -jnp.inf, ids, _SENTINEL)
    total = jnp.sum(passed.astype(jnp.int32))
    return vals, ids, total


_plan_topk_impl = tracked_jit(
    "plan_topk", static_argnames=("k", "combine", "k1", "b", "with_dense",
                                  "with_after", "script_fn"))(plan_topk_body)


def pack_result(vals: jax.Array, ids: jax.Array,
                total: jax.Array) -> jax.Array:
    """Pack (vals [k] f32, ids [k] i32, total i32) into ONE [2k+1] f32
    buffer. The axon tunnel charges ~100ms per device→host readback in
    its degraded mode — one packed readback per launch instead of three
    is a 3× serving-latency lever.

    Ints ride as FLOAT CASTS, not bitcasts: float32 represents every
    integer < 2^24 exactly (doc ids and totals are bounded by segment
    doc count, << 2^24), and the axon runtime MISCOMPILES concats with
    more than one bitcast section — computed int32 data read back as
    zeros, shape-dependently (observed r5: correct hits, total=0; also
    reproducible with ids zeroed). The sentinel id (2^31-1) is not
    f32-exact but is never read (callers mask by finite vals first)."""
    return jnp.concatenate([
        vals.astype(jnp.float32),
        ids.astype(jnp.float32),
        jnp.reshape(total, (1,)).astype(jnp.float32),
    ])


def unpack_ids(buf: np.ndarray) -> np.ndarray:
    """Float-packed int lanes -> int32, sentinel-safe. The cast ORDER
    is load-bearing: the sentinel rides as 2^31 exactly, which float32
    CAN represent but int32 can't — a direct cast is UB, and np.clip
    in f32 can't even express 2^31-1. int64 first, then clip, then
    narrow. Every packed-readback unpacker must go through this."""
    return np.clip(buf.astype(np.int64), 0, 0x7FFFFFFF).astype(np.int32)


def unpack_result(buf: np.ndarray, k: int):
    """Host-side inverse of pack_result on an np.float32 [2k+1] row."""
    vals = buf[:k]
    ids = unpack_ids(buf[k:2 * k])
    total = int(buf[2 * k])
    return vals, ids, total


def _plan_topk_packed_body(streams, group_kind, group_req, group_const,
                           live, dense_mask, n_must, n_filter, msm,
                           bonus, tie, after_score, k1, b, k, combine,
                           with_dense, with_after=False, script_fn=None):
    return pack_result(*plan_topk_body(
        streams, group_kind, group_req, group_const, live, dense_mask,
        n_must, n_filter, msm, bonus, tie, after_score, k1, b, k,
        combine, with_dense, with_after, script_fn))


_plan_topk_packed_impl = tracked_jit(
    "plan_topk_packed",
    static_argnames=("k", "combine", "k1", "b", "with_dense",
                     "with_after", "script_fn"))(_plan_topk_packed_body)


def plan_topk(streams, group_kind, group_req, group_const, live,
              dense_mask: Optional[jax.Array],
              n_must: int, n_filter: int, msm: int,
              bonus: float = 0.0, tie: float = 0.0,
              k1: float = 1.2, b: float = 0.75, k: int = 10,
              combine: str = "sum",
              after_score: Optional[float] = None,
              packed: bool = False, script_fn=None):
    """Single-query entry. ``dense_mask=None`` skips the gather entirely
    (the common pure-postings case compiles without it). ``packed=True``
    returns ONE [2k+1] device buffer (see pack_result) for single-readback
    serving."""
    with_dense = dense_mask is not None
    if not with_dense:
        dense_mask = jnp.ones(1, bool)  # placeholder, not read
    with_after = after_score is not None
    impl = _plan_topk_packed_impl if packed else _plan_topk_impl
    return impl(
        tuple(streams), np.asarray(group_kind, np.int32),
        np.asarray(group_req, np.int32),
        np.asarray(group_const, np.float32), live, dense_mask,
        np.int32(n_must), np.int32(n_filter), np.int32(msm),
        np.float32(bonus), np.float32(tie),
        np.float32(after_score if with_after else 0.0),
        float(k1), float(b), int(k), combine, with_dense, with_after,
        script_fn)


@tracked_jit("plan_topk_batch",
             static_argnames=("k", "combine", "k1", "b", "with_dense",
                              "script_fn"))
def _plan_topk_batch_impl(streams, group_kind, group_req, group_const,
                          live, dense_mask, n_must, n_filter, msm,
                          bonus, tie, k1, b, k, combine, with_dense,
                          script_fn=None):
    """vmap over the query axis of the selection/group arrays; corpus
    arrays are shared (in_axes=None), and so is the optional dense
    filter mask — cohorts are keyed by filter identity (the cached
    composed column), so one [ND] mask serves the whole batch with no
    per-query stacking."""

    def one(sel_blocks, sel_group, sel_sub, sel_weight, sel_const,
            gk, gr, gcst, nm, nf, ms, bo, ti):
        sts = tuple(
            FieldStream(st.block_docids, st.block_tfs, st.doc_lens,
                        st.avg_len, sb, sg, ss, sw, sc)
            for st, sb, sg, ss, sw, sc in zip(
                streams, sel_blocks, sel_group, sel_sub, sel_weight,
                sel_const))
        return pack_result(*plan_topk_body(
            sts, gk, gr, gcst, live, dense_mask,
            nm, nf, ms, bo, ti, jnp.float32(0.0),
            k1, b, k, combine, with_dense, script_fn=script_fn))

    sel_b = tuple(st.sel_blocks for st in streams)   # each [Q, NB]
    sel_g = tuple(st.sel_group for st in streams)
    sel_s = tuple(st.sel_sub for st in streams)
    sel_w = tuple(st.sel_weight for st in streams)
    sel_c = tuple(st.sel_const for st in streams)
    return jax.vmap(one)(sel_b, sel_g, sel_s, sel_w, sel_c,
                         group_kind, group_req, group_const,
                         n_must, n_filter, msm, bonus, tie)


def plan_topk_batch(streams, group_kind, group_req, group_const, live,
                    n_must, n_filter, msm, bonus, tie,
                    k1: float = 1.2, b: float = 0.75, k: int = 10,
                    combine: str = "sum", dense_mask=None,
                    script_fn=None):
    """Batched entry: every per-query array has a leading [Q] axis; the
    corpus arrays inside ``streams`` stay unbatched (shared), as is the
    optional [ND] ``dense_mask`` (one filter column for the whole
    cohort). Returns PACKED [Q, 2k+1] rows (pack_result) — one readback
    serves the whole batch. This is the continuous-batching launch
    shape (SURVEY.md §7 hard part 5)."""
    with_dense = dense_mask is not None
    if not with_dense:
        dense_mask = jnp.ones(1, bool)   # placeholder, not read
    return _plan_topk_batch_impl(
        tuple(streams), np.asarray(group_kind, np.int32),
        np.asarray(group_req, np.int32),
        np.asarray(group_const, np.float32), live, dense_mask,
        np.asarray(n_must, np.int32), np.asarray(n_filter, np.int32),
        np.asarray(msm, np.int32), np.asarray(bonus, np.float32),
        np.asarray(tie, np.float32),
        float(k1), float(b), int(k), combine, with_dense, script_fn)


@tracked_jit("plan_topk_mesh",
             static_argnames=("mesh", "nd", "n_must", "n_filter", "msm",
                              "tie", "k1", "b", "k", "combine"))
def plan_topk_mesh(streams, group_kind, group_req, group_const, bonus,
                   live, mesh, nd: int, n_must: int, n_filter: int,
                   msm: int, tie: float, k1: float, b: float, k: int,
                   combine: str):
    """ONE SPMD program for a multi-shard query over a device mesh: the
    TransportSearchAction scatter-gather re-expressed as collectives.

    Every input carries a leading shard axis, sharded ``P("shard")``
    (parallel/mesh_executor.py stacks per-shard selections/corpora this
    way); each device scores its own shard with :func:`plan_topk_body`,
    then ONE ``all_gather`` over the shard axis + on-device re-top-k
    replaces the coordinator merge and a ``psum`` the total-hits
    accumulation. Returns a replicated packed [2k+1] buffer
    (:func:`pack_result`) — one readback for the whole mesh query.

    Global ids are ``shard * nd + local`` in int32: the packed float
    readback bounds them below ``PACKED_ID_LIMIT`` (2^24), enforced by
    the caller, so int32 can never overflow here."""
    from jax.sharding import PartitionSpec as P

    from elasticsearch_tpu.utils.jax_compat import shard_map

    in_specs = (tuple(FieldStream(*([P("shard")] * 9)) for _ in streams),
                P("shard"), P("shard"), P("shard"), P("shard"),
                P("shard"))

    @shard_map(mesh=mesh, check_vma=False, in_specs=in_specs,
               out_specs=P())
    def step(sts, gk, gr, gc, bo, lv):
        local = tuple(
            FieldStream(st.block_docids[0], st.block_tfs[0],
                        st.doc_lens[0], st.avg_len[0],
                        st.sel_blocks[0], st.sel_group[0],
                        st.sel_sub[0], st.sel_weight[0],
                        st.sel_const[0])
            for st in sts)
        vals, ids, total = plan_topk_body(
            local, gk[0], gr[0], gc[0], lv[0], jnp.ones(1, bool),
            jnp.int32(n_must), jnp.int32(n_filter), jnp.int32(msm),
            bo[0], jnp.float32(tie), jnp.float32(0.0),
            k1, b, k, combine, False, False)
        shard_idx = jax.lax.axis_index("shard").astype(jnp.int32)
        gids = jnp.where(ids == _SENTINEL, _SENTINEL,
                         ids + shard_idx * nd)
        # ONE all_gather over ICI + on-device re-top-k = coordinator merge
        av = jax.lax.all_gather(vals, "shard")        # [S, k]
        ag = jax.lax.all_gather(gids, "shard")
        tv, ti = jax.lax.top_k(av.reshape(-1), k)
        tg = jnp.take(ag.reshape(-1), ti)
        tg = jnp.where(tv > -jnp.inf, tg, _SENTINEL)
        # pack → one readback for the whole mesh query
        return pack_result(tv, tg, jax.lax.psum(total, "shard"))

    return step(tuple(streams), group_kind, group_req, group_const,
                bonus, live)


# ---------------------------------------------------------------------------
# Impact-ordered block selection (host-side, pure numpy).
#
# Lucene's impact-ordered postings let block-max WAND spend its
# evaluation budget on the blocks with the highest score upper bounds
# instead of the lowest docids (ref: Lucene ImpactsEnum /
# MaxScoreBulkScorer). The TPU analogue: the serving fast path selects
# postings BLOCKS into a fixed lane budget per launch, so WHICH blocks
# enter the budget decides recall-at-budget. These helpers precompute a
# per-block BM25 upper bound at registration (block-max tf × idf, the
# same bound the θ/MaxScore lane derives), order each term's block list
# by descending bound once, and select per query under a budget by
# impact — with the residual bound of everything excluded, so callers
# can run the block-max safe-termination check (no unseen doc can reach
# the kth score) on readback.
#
# Layout convention: term t's blocks occupy the contiguous index range
# [starts[t], starts[t]+counts[t]) of the block arrays, docid-ascending
# by block index. ``order``/``ub_desc`` use the SAME flat layout, but
# within each term's range the entries are impact-sorted: position
# starts[t]+j holds the block id (resp. bound) of t's (j+1)-th
# highest-impact block.
# ---------------------------------------------------------------------------


class TermImpacts(NamedTuple):
    """Registration-time impact metadata for one postings field."""

    ub: np.ndarray        # float64 [TB] per-block score upper bound
    order: np.ndarray     # int32 [TB] impact-sorted block ids per term
    ub_desc: np.ndarray   # float64 [TB] bounds in `order`'s layout


def build_term_impacts(starts, counts, block_max_tf, block_min_len,
                       idf, avg_len: float, k1: float,
                       b: float) -> TermImpacts:
    """Per-block BM25 upper bounds + per-term impact ordering.

    The bound is the block-max saturation at the block's minimum length
    times the term's idf — the max contribution ANY doc in the block can
    make (the same quantity the θ-lane's ``maxc`` takes the per-term max
    of). Empty blocks (max tf 0) bound to 0."""
    starts = np.asarray(starts, np.int64)
    counts = np.asarray(counts, np.int64)
    mtf = np.asarray(block_max_tf, np.float64)
    mln = np.asarray(block_min_len, np.float64)
    sat = np.where(mtf > 0,
                   mtf / (mtf + k1 * (1.0 - b + b * mln / avg_len)), 0.0)
    tb = mtf.shape[0]
    # term id owning each block: the packed layout is contiguous and
    # gap-free (segment.py builds starts as the exact cumsum of
    # counts) — enforce loudly, a gap would silently shift every
    # term's impact range (the check_packed_id_limit style)
    if int(counts.sum()) != tb:
        raise ValueError(
            f"packed block layout violated: sum(counts)="
            f"{int(counts.sum())} != n_blocks={tb}")
    term_of = np.repeat(np.arange(len(counts)), counts)
    ub = sat * np.asarray(idf, np.float64)[term_of]
    # impact order per term: argsort of (term, -ub, block) — one global
    # stable sort keeps it vectorized; ties keep docid (block) order
    order = np.lexsort((np.arange(tb), -ub, term_of)).astype(np.int32)
    return TermImpacts(ub=ub, order=order, ub_desc=ub[order])


def select_blocks_impact(term_ids, budget: int, starts, counts,
                         impacts: TermImpacts):
    """Budgeted per-query block selection by descending impact.

    Returns ``(per_term, miss_bound)``: ``per_term`` is a list of int32
    arrays (one per term id, ASCENDING block ids — the slot-sorted
    invariant the merge kernels require), ``miss_bound`` the sum over
    terms of the max bound among that term's EXCLUDED blocks (a doc
    appears in at most one block per term, so no doc's true score can
    exceed its observed score by more than ``miss_bound``; an entirely
    unseen doc is bounded by ``miss_bound`` itself). ``miss_bound`` is
    0.0 exactly when the selection is complete (exact serving)."""
    segs = [(int(starts[t]), int(counts[t])) for t in term_ids]
    total = sum(c for _, c in segs)
    if total <= budget:
        return ([np.arange(s, s + c, dtype=np.int32) for s, c in segs],
                0.0)
    ud = impacts.ub_desc
    cat = np.concatenate([ud[s:s + c] for s, c in segs])
    # threshold = budget-th largest bound; strictly-greater blocks are
    # all in, ties fill the remainder in term order (deterministic)
    thr = np.partition(cat, total - budget)[total - budget]
    n_gt = [int(np.searchsorted(-ud[s:s + c], -thr, side="left"))
            for s, c in segs]
    spare = budget - sum(n_gt)
    per_term: list = []
    miss = 0.0
    for (s, c), j in zip(segs, n_gt):
        # extend through the tie band while budget remains
        while spare > 0 and j < c and ud[s + j] == thr:
            j += 1
            spare -= 1
        take = impacts.order[s:s + j]
        per_term.append(np.sort(take).astype(np.int32))
        if j < c:
            miss += float(ud[s + j])
    return per_term, miss


def select_blocks_prefix(term_ids, budget: int, starts, counts):
    """Posting-order baseline: each term keeps the PREFIX of its block
    list, lowest docids first, dropping tail blocks round-robin until
    the budget fits (the selection a budget-blind path would make).
    Same return convention as :func:`select_blocks_impact` minus the
    bound (callers compare recall, not certificates)."""
    cnts = [int(counts[t]) for t in term_ids]
    while sum(cnts) > budget:
        i = int(np.argmax(cnts))
        over = sum(cnts) - budget
        cnts[i] = max(0, cnts[i] - max(1, min(over, cnts[i] // 2)))
    return [np.arange(int(starts[t]), int(starts[t]) + c, dtype=np.int32)
            for t, c in zip(term_ids, cnts)]


def impact_safe_termination(kth: float, next_best: float,
                            miss_bound: float) -> bool:
    """The block-max safe-termination check on a truncated launch's
    readback: with every doc's possible gain bounded by ``miss_bound``,
    the observed top-k SET is provably the true top-k when the best
    excluded candidate (``next_best``: the (k+1)-th observed score, or
    0.0 when fewer than k+1 docs matched — an unseen doc's observed
    score) cannot close the gap to the kth. Observed scores of the
    returned docs remain lower bounds (callers report totals with
    relation ``gte``)."""
    if miss_bound <= 0.0:
        return True
    if not np.isfinite(kth):
        return False          # fewer than k hits: unseen docs could fill
    floor = max(float(next_best) if np.isfinite(next_best) else 0.0, 0.0)
    return floor + miss_bound < kth


# ---------------------------------------------------------------------------
# Scatter-free dense builders (for the fallback path: aggs need full masks)
# ---------------------------------------------------------------------------

def _unique_scatter_indices(dkey: jax.Array, is_last: jax.Array,
                            nd: int) -> jax.Array:
    """Strictly-unique scatter targets: run-last lanes write their docid,
    every other lane writes a distinct out-of-bounds slot (dropped).
    Guaranteed-unique indices let XLA emit a parallel scatter instead of
    the serialized duplicate-handling form (the ~70ms trap)."""
    lane = jnp.arange(dkey.shape[0], dtype=jnp.int32)
    return jnp.where(is_last & (dkey != _SENTINEL), dkey, nd + lane)


@tracked_jit(static_argnames=("k1", "b", "max_run"))
def bm25_dense_scores_sorted(block_docids, block_tfs, sel_blocks,
                             sel_weights, doc_lens, avg_len,
                             k1: float, b: float, max_run: int = 32):
    """Dense per-doc BM25 scores [ND] via sort + DOUBLING segmented sum
    + ONE unique-index scatter — the scatter-free replacement for
    ops/bm25.bm25_block_scores (whose scatter-add serializes on TPU).
    This is the scorer behind the dense path — every aggs/sort/script
    query rides it (VERDICT r2 item 3: aggs were paying the serialized
    scatter). The doubling scan keeps full f32 accuracy — a global
    cumsum's prefix error reorders boundary docs at corpus scale.

    ``max_run`` must bound the longest per-doc run (= the number of term
    INSTANCES in the selection: one entry per term per doc). Callers
    with unbounded term counts (analyzed match text, fuzzy/wildcard
    expansions) pass ``scan_run_bound(n_terms)`` — a 31-term query under
    the old fixed cap of 32 silently dropped contributions."""
    d = jnp.take(block_docids, sel_blocks, axis=0)
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    dl = jnp.take(doc_lens, d)
    norm = k1 * (1.0 - b + b * dl / avg_len)
    contrib = sel_weights[:, None] * jnp.where(tf > 0.0, tf / (tf + norm), 0.0)

    dflat = d.reshape(-1)
    cflat = contrib.reshape(-1)
    valid = tf.reshape(-1) > 0.0
    dkey = jnp.where(valid, dflat, _SENTINEL)
    dkey, c = jax.lax.sort((dkey, jnp.where(valid, cflat, 0.0)), num_keys=1)
    x = c
    step = 1
    while step < min(max_run, dkey.shape[0]):
        prev_x = jnp.pad(x[:-step], (step, 0))
        prev_k = jnp.pad(dkey[:-step], (step, 0), constant_values=-1)
        x = x + jnp.where(prev_k == dkey, prev_x, 0.0)
        step *= 2
    new_doc = dkey != _prev(dkey, -1)
    is_last = jnp.concatenate([new_doc[1:], jnp.ones(1, bool)])
    nd = doc_lens.shape[0]
    idx = _unique_scatter_indices(dkey, is_last, nd)
    scores = jnp.zeros(nd, jnp.float32)
    return scores.at[idx].set(x, mode="drop", unique_indices=True)


@tracked_jit
def match_count_sorted(block_docids, block_tfs, sel_blocks, clause_ids,
                       live_template):
    """int32 [ND] distinct-clause counts via sort + run boundaries + ONE
    unique-index scatter — the scatter-free replacement for
    ops/bm25.match_count (bool must / minimum_should_match on the dense
    fallback path). ``live_template`` only supplies ND."""
    d = jnp.take(block_docids, sel_blocks, axis=0)           # [NB, B]
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    cid = jnp.broadcast_to(clause_ids[:, None], d.shape)
    dflat, cflat = d.reshape(-1), cid.reshape(-1)
    valid = tf.reshape(-1) > 0.0
    dkey = jnp.where(valid, dflat, _SENTINEL)
    dkey, cl = jax.lax.sort((dkey, cflat), num_keys=2)
    new_doc = dkey != _prev(dkey, -1)
    new_pair = new_doc | (cl != _prev(cl, -1))
    is_last = jnp.concatenate([new_doc[1:], jnp.ones(1, bool)])
    counts = _segsum(new_pair.astype(jnp.float32), new_doc)
    nd = live_template.shape[0]
    idx = _unique_scatter_indices(dkey, is_last, nd)
    out = jnp.zeros(nd, jnp.int32)
    return out.at[idx].set(counts.astype(jnp.int32), mode="drop",
                           unique_indices=True)


@tracked_jit
def match_mask_sorted(block_docids, block_tfs, sel_blocks, live_template):
    """bool [ND] any-of mask via the same unique-scatter trick — the
    scatter-free replacement for ops/bm25.match_mask."""
    d = jnp.take(block_docids, sel_blocks, axis=0)
    tf = jnp.take(block_tfs, sel_blocks, axis=0)
    dflat = d.reshape(-1)
    valid = tf.reshape(-1) > 0.0
    dkey = jnp.where(valid, dflat, _SENTINEL)
    dkey = jax.lax.sort(dkey)
    new_doc = dkey != _prev(dkey, -1)
    is_last = jnp.concatenate([new_doc[1:], jnp.ones(1, bool)])
    nd = live_template.shape[0]
    idx = _unique_scatter_indices(dkey, is_last, nd)
    out = jnp.zeros(nd, bool)
    return out.at[idx].set(jnp.ones_like(dkey, bool), mode="drop",
                           unique_indices=True)
