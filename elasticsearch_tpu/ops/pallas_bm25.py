"""Pallas TPU kernel for the BM25 contribution stage.

The scoring hot path (ops/bm25.py `bm25_sorted_topk`) is: gather blocks →
per-posting BM25 contribution → sort-based segmented reduction → top-k.
The contribution stage is pure elementwise VPU math; this Pallas kernel
fuses it into one tiled pass over the gathered (tf, dl) planes —
weight · tf / (tf + k1·(1 − b + b·dl/avg)) — with the tf=0 padding-lane
guard folded in, so XLA cannot split it into multiple HBM round-trips
(the pallas_guide playbook: explicit VMEM tiling for bandwidth-bound
elementwise chains).

Measured on a TPU v5e chip the kernel is at PARITY with the jnp
expression (XLA fuses this elementwise chain just as well — the
pallas_guide's own advice: don't hand-schedule what the compiler
already fuses), so the default hot path keeps the jnp form and this
module stands as the maintained Pallas alternative: property-tested
against the reference expression, ready for the ops where explicit
tiling DOES pay (block-max pruning with scalar prefetch is the next
candidate). On CPU backends the kernel runs in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.telemetry.engine import tracked_jit

_BLOCK = 128          # postings block width (index/segment.py BLOCK_SIZE)
_TILE_ROWS = 256      # selection rows per grid step


def _contrib_kernel(w_ref, avg_ref, tf_ref, dl_ref, o_ref, *, k1, b):
    tf = tf_ref[...]
    dl = dl_ref[...]
    w = w_ref[...]                          # [rows, 1] — broadcasts
    avg = avg_ref[0]
    norm = k1 * (1.0 - b + b * dl * (1.0 / avg))
    o_ref[...] = jnp.where(tf > 0.0, w * tf / (tf + norm), 0.0)


@tracked_jit(static_argnames=("k1", "b"))
def bm25_contrib_pallas(sel_weights: jax.Array,   # float32 [NB]
                        tf: jax.Array,            # float32 [NB, 128]
                        dl: jax.Array,            # float32 [NB, 128]
                        avg_len, k1: float, b: float) -> jax.Array:
    """Fused contribution plane [NB, 128] via a tiled Pallas kernel.

    Weights stream as an [NB, 1] column (broadcast happens in VMEM, not
    as a materialized HBM plane) and avg_len stays a TRACED scalar so the
    signature matches the jnp hot path (no recompiles per refresh)."""
    from jax.experimental import pallas as pl

    nb = tf.shape[0]
    if nb == 0:
        return jnp.zeros_like(tf)
    rows = _TILE_ROWS if (nb % _TILE_ROWS == 0) else nb
    grid = (nb // rows,)
    kernel = functools.partial(_contrib_kernel, k1=k1, b=b)
    spec = pl.BlockSpec((rows, _BLOCK), lambda i: (i, 0))
    w_spec = pl.BlockSpec((rows, 1), lambda i: (i, 0))
    avg_spec = pl.BlockSpec((1,), lambda i: (0,))
    avg_arr = jnp.asarray(avg_len, jnp.float32).reshape(1)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[w_spec, avg_spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(tf.shape, jnp.float32),
        interpret=(jax.default_backend() != "tpu"),
    )(sel_weights[:, None], avg_arr, tf, dl)


def contrib_reference(sel_weights, tf, dl, avg_len, k1, b):
    """The jnp reference the kernel is property-tested against — THE
    shared scoring expression from ops/bm25.py."""
    from elasticsearch_tpu.ops.bm25 import bm25_contrib
    return bm25_contrib(jnp.asarray(sel_weights), jnp.asarray(tf),
                        jnp.asarray(dl), avg_len, k1, b)


def pallas_available() -> bool:
    """True when the default backend compiles Pallas TPU kernels (only
    tpu — other backends run interpret mode)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
