"""Device-side aggregation collectors (round-4, VERDICT r3 item 6).

The reference's aggregation framework collects per-doc through
LeafBucketCollector callbacks (ref: server/.../search/aggregations/
AggregatorBase.java:180-186 — getLeafCollector → per-doc collect()).
The TPU-native recast: the hot bucket/metric collectors are BATCHED
SEGMENT REDUCTIONS over columnar doc values — no per-doc host code.
This module holds the device half: terms counts ride a per-field
ORD-MAJOR docid permutation built once per (immutable) device segment —
gather the query mask through the permutation, one inclusive cumsum,
take the per-term boundary positions, diff — exact per-term doc counts
in 3 array ops (the same sorted-segmented-reduction shape as the
scoring kernels).

Numeric metric chains and histogram bucketing ride the device too
(round-7): ``masked_metric_stats`` fuses count/sum/min/max/sum-of-
squares into ONE launch over a resident f32 column, and the histogram
family scatter-adds doc→bucket ids into per-bucket count + sub-metric
columns — one launch per (segment, metric column) instead of one host
numpy pass per bucket. Bucket-id ARITHMETIC stays host-side in f64
(epoch-millisecond keys exceed f32's integer range; a floor-divide
over a column is cheap) — the device takes the REDUCTION, which is the
part that scales with doc count and bucket count. Bucket counts pad to
a power-of-two ladder (``_pow2_buckets``) so recompiles stay bounded
and visible in ``GET /_kernels``; dispatch thresholds and the exact
host fallback live in search/aggregations.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops import device as device_ops
from elasticsearch_tpu.telemetry.engine import tracked_jit

# buckets beyond this cap stay on the host unique/bincount path (a
# scatter this wide stops paying for the launch)
AGG_BUCKET_CAP = 8192
_F32_BIG = float(np.finfo(np.float32).max)


@tracked_jit("terms_counts")
def _terms_counts_kernel(perm_docs, mask, ends_idx, begins_idx,
                         begins_zero, nonempty):
    """counts[i] = cum[start_{i+1}-1] - cum[start_i-1] over the masked
    hits gathered through the ord-major permutation."""
    hits = jnp.take(mask, perm_docs).astype(jnp.int32)
    cum = jnp.cumsum(hits)
    ends = jnp.take(cum, ends_idx)
    begins = jnp.where(begins_zero, 0, jnp.take(cum, begins_idx))
    return jnp.where(nonempty, ends - begins, 0)


def terms_counts_per_term(dev_perm, term_starts: np.ndarray,
                          mask) -> np.ndarray:
    """Per-term masked doc counts [n_terms] — ONE [total] gather + ONE
    cumsum on device, one [n_terms] readback."""
    total = int(dev_perm.shape[0])
    ends_idx = np.clip(term_starts[1:] - 1, 0, max(total - 1, 0)
                       ).astype(np.int32)
    begins_idx = np.clip(term_starts[:-1] - 1, 0, max(total - 1, 0)
                         ).astype(np.int32)
    begins_zero = (term_starts[:-1] == 0)
    nonempty = (term_starts[1:] > term_starts[:-1])
    out = _terms_counts_kernel(dev_perm, mask, ends_idx, begins_idx,
                               begins_zero, nonempty)
    return device_ops.readback("ops.aggs.terms_counts",
                               out).astype(np.int64)


# ---------------------------------------------------------------------------
# metric reductions (round-7): one launch per (segment, column)
# ---------------------------------------------------------------------------

@tracked_jit("agg_metric_stats")
def _metric_stats_kernel(values, missing, mask):
    """count/sum/min/max/sum-of-squares of a masked f32 column, fused —
    the device half of sum/avg/min/max/stats/extended_stats."""
    sel = jnp.logical_and(mask, jnp.logical_not(missing))
    v = jnp.where(sel, values, 0.0)
    n = jnp.sum(sel.astype(jnp.int32))
    s = jnp.sum(v)
    ss = jnp.sum(v * v)
    mn = jnp.min(jnp.where(sel, values, jnp.float32(_F32_BIG)))
    mx = jnp.max(jnp.where(sel, values, jnp.float32(-_F32_BIG)))
    return n, s, mn, mx, ss


def masked_metric_stats(dev_values, dev_missing, dev_mask):
    """(count, sum, min, max, sum_sq) over masked present values —
    one launch, one scalar readback. min/max are None when count is 0."""
    n, s, mn, mx, ss = _metric_stats_kernel(dev_values, dev_missing,
                                            dev_mask)
    n = int(n)
    if n == 0:
        return 0, 0.0, None, None, 0.0
    return n, float(s), float(mn), float(mx), float(ss)


# ---------------------------------------------------------------------------
# histogram bucketing via scatter-add (round-7)
# ---------------------------------------------------------------------------

def pow2_buckets(nb: int) -> int:
    """Pad a bucket count to the power-of-two ladder (floor 64) so the
    scatter kernels compile once per ladder rung, not once per query;
    0 when past AGG_BUCKET_CAP (caller falls back to the host path)."""
    if nb <= 0 or nb > AGG_BUCKET_CAP:
        return 0
    p = 64
    while p < nb:
        p <<= 1
    return p


@tracked_jit("agg_bucket_counts", static_argnames=("nb",))
def _bucket_counts_kernel(bucket_ids, mask, nb):
    """Per-bucket masked doc counts: ONE scatter-add into nb+1 slots
    (slot nb swallows masked-out docs)."""
    ids = jnp.where(mask, bucket_ids, nb)
    return jnp.zeros(nb + 1, jnp.int32).at[ids].add(1)[:nb]


def bucket_counts(dev_bucket_ids, dev_mask, nb: int) -> np.ndarray:
    """Host int64 counts [nb] from one device scatter-add launch.
    ``dev_bucket_ids`` int32 in [0, nb) for in-range docs (out-of-range
    ids must already be masked out)."""
    nb_pad = pow2_buckets(nb)
    if nb_pad == 0:
        raise ValueError(f"bucket count {nb} past AGG_BUCKET_CAP")
    out = _bucket_counts_kernel(dev_bucket_ids, dev_mask, nb_pad)
    return device_ops.readback("ops.aggs.bucket_counts",
                               out)[:nb].astype(np.int64)


@tracked_jit("agg_bucket_metrics", static_argnames=("nb",))
def _bucket_metrics_kernel(bucket_ids, mask, values, missing, nb):
    """Per-bucket count/sum/min/max/sum-of-squares of a metric column:
    the whole per-bucket sub-metric chain in ONE launch (vs one host
    numpy pass per bucket)."""
    sel = jnp.logical_and(mask, jnp.logical_not(missing))
    ids = jnp.where(sel, bucket_ids, nb)
    v = jnp.where(sel, values, 0.0)
    cnt = jnp.zeros(nb + 1, jnp.int32).at[ids].add(1)
    s = jnp.zeros(nb + 1, jnp.float32).at[ids].add(v)
    ss = jnp.zeros(nb + 1, jnp.float32).at[ids].add(v * v)
    big = jnp.float32(_F32_BIG)
    mn = jnp.full(nb + 1, big, jnp.float32).at[ids].min(
        jnp.where(sel, values, big))
    mx = jnp.full(nb + 1, -big, jnp.float32).at[ids].max(
        jnp.where(sel, values, -big))
    return cnt[:nb], s[:nb], mn[:nb], mx[:nb], ss[:nb]


def bucket_metric_columns(dev_bucket_ids, dev_mask, dev_values,
                          dev_missing, nb: int):
    """Host (count, sum, min, max, sum_sq) arrays [nb] for one metric
    column across all buckets — one launch per (segment, column).
    min/max entries of empty buckets come back as ±f32-max; the caller
    masks them against count == 0."""
    nb_pad = pow2_buckets(nb)
    if nb_pad == 0:
        raise ValueError(f"bucket count {nb} past AGG_BUCKET_CAP")
    cnt, s, mn, mx, ss = _bucket_metrics_kernel(
        dev_bucket_ids, dev_mask, dev_values, dev_missing, nb_pad)
    cnt, s, mn, mx, ss = device_ops.readback(
        "ops.aggs.bucket_metrics", cnt, s, mn, mx, ss)
    return (cnt[:nb].astype(np.int64),
            s[:nb].astype(np.float64),
            mn[:nb].astype(np.float64),
            mx[:nb].astype(np.float64),
            ss[:nb].astype(np.float64))
