"""Device-side aggregation collectors (round-4, VERDICT r3 item 6).

The reference's aggregation framework collects per-doc through
LeafBucketCollector callbacks (ref: server/.../search/aggregations/
AggregatorBase.java:180-186 — getLeafCollector → per-doc collect()).
The TPU-native recast: the hot bucket/metric collectors are BATCHED
SEGMENT REDUCTIONS over columnar doc values — no per-doc host code.
This module holds the device half: terms counts ride a per-field
ORD-MAJOR docid permutation built once per (immutable) device segment —
gather the query mask through the permutation, one inclusive cumsum,
take the per-term boundary positions, diff — exact per-term doc counts
in 3 array ops (the same sorted-segmented-reduction shape as the
scoring kernels).

Histogram counts and numeric metric reductions stay HOST-side but
batched (one-pass np.unique / masked column reductions in
search/aggregations.py): their inputs need f64 (epoch-millisecond keys
and sum accumulation exceed f32's integer range) while the device
columns are f32, and a single fused host pass already beats a device
round-trip through the serving tunnel.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from elasticsearch_tpu.telemetry.engine import tracked_jit


@tracked_jit("terms_counts")
def _terms_counts_kernel(perm_docs, mask, ends_idx, begins_idx,
                         begins_zero, nonempty):
    """counts[i] = cum[start_{i+1}-1] - cum[start_i-1] over the masked
    hits gathered through the ord-major permutation."""
    hits = jnp.take(mask, perm_docs).astype(jnp.int32)
    cum = jnp.cumsum(hits)
    ends = jnp.take(cum, ends_idx)
    begins = jnp.where(begins_zero, 0, jnp.take(cum, begins_idx))
    return jnp.where(nonempty, ends - begins, 0)


def terms_counts_per_term(dev_perm, term_starts: np.ndarray,
                          mask) -> np.ndarray:
    """Per-term masked doc counts [n_terms] — ONE [total] gather + ONE
    cumsum on device, one [n_terms] readback."""
    total = int(dev_perm.shape[0])
    ends_idx = np.clip(term_starts[1:] - 1, 0, max(total - 1, 0)
                       ).astype(np.int32)
    begins_idx = np.clip(term_starts[:-1] - 1, 0, max(total - 1, 0)
                         ).astype(np.int32)
    begins_zero = (term_starts[:-1] == 0)
    nonempty = (term_starts[1:] > term_starts[:-1])
    out = _terms_counts_kernel(dev_perm, mask, ends_idx, begins_idx,
                               begins_zero, nonempty)
    return np.asarray(out).astype(np.int64)
