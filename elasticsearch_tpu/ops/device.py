"""Device-resident segment state.

The analogue of Lucene's on-heap/off-heap segment readers, re-homed in TPU
HBM: a DeviceSegment uploads a segment's postings blocks, norms, live mask
and vector slabs to the device once; every query then only ships a few
hundred bytes of block ids and weights (the "JNI→JAX bridge" data plane of
BASELINE.json, without a process hop).

Shape discipline for XLA caching (everything under jit compiles per shape,
SURVEY.md §7 "hard parts" #2):
- doc count pads to a multiple of ``DOC_PAD`` (padded docs are dead in the
  live mask and have doc_len = avg so no NaN/0-div),
- one reserved all-zeros postings block sits at index ``num_blocks`` —
  query block lists pad with it (weight 0) and bucket to powers of two,
  so NB only takes O(log) distinct values across queries.
"""

from __future__ import annotations

from collections import OrderedDict
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.index.segment import BLOCK_SIZE, Segment
from elasticsearch_tpu.ops.vector import prepare_vectors

DOC_PAD = 1024
MIN_BLOCK_BUCKET = 8

# Filter-mask cache knobs (per DeviceSegment). Each entry is one bool
# column: n_docs_padded bytes on device + the same on host (the host copy
# validates block-max pruning thresholds without a device readback).
FILTER_MASK_CACHE_MAX = 64

# HBM slab classes — the accounting buckets of `GET /_nodes/stats`'s
# engine section (the TPU-native analogue of the reference's segment
# stats + fielddata memory accounting in NodeIndicesStats). Every
# device-resident array of a DeviceSegment belongs to exactly one class,
# so `sum(hbm_bytes_by_class().values()) == hbm_bytes()` by construction.
HBM_SLAB_CLASSES = ("postings", "norms", "live_mask", "vectors",
                    "doc_values", "ordinals", "filter_masks")


def readback(site: str, *arrays, profile: bool = True):
    """THE tracked device→host funnel: every product-path transfer of a
    jitted output to host memory goes through here so its call site,
    byte count, and duration land in the per-node flight recorder
    (telemetry/flightrecorder.py) — provenance for the post-readback
    degraded regime. estpu-lint's ESTPU-RB rules flag ``np.asarray`` /
    ``jax.device_get`` / ``.block_until_ready()`` on jitted outputs
    anywhere else in the engine dirs, keeping attribution total.

    ``site`` is a stable dotted label (``"search.batching.plan_cohort"``);
    returns the host array for one input, a tuple for several. Also
    feeds the per-request ``profile: true`` readback counters, so the
    two sites that used to hand-roll that share one implementation.
    Costs two TLS getattrs plus the transfer when nothing is ambient.
    """
    from elasticsearch_tpu.search import profile as _prof
    from elasticsearch_tpu.telemetry import flightrecorder as _flight
    fr = _flight.current()
    # profile=False: cohort-wide transfers (the batcher's ONE packed
    # readback) keep per-entry attribution in their cohort meta instead
    # of charging the whole cohort's bytes to the leader's request
    prof_on = profile and _prof.recording()
    t_prof = _prof.now_ns() if prof_on else 0
    t_fr = fr.clock() if fr is not None else 0.0
    out = tuple(np.asarray(a) for a in arrays)
    if fr is not None:
        fr.record_readback(
            site, sum(int(a.nbytes) for a in out),
            duration_ns=int((fr.clock() - t_fr) * 1e9))
    if prof_on:
        _prof.record_readback(t_prof, *out)
    return out[0] if len(out) == 1 else out


def round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def block_bucket(n: int) -> int:
    """Round a selected-block count up to the next power-of-two bucket."""
    b = MIN_BLOCK_BUCKET
    while b < n:
        b *= 2
    return b


def host_any_mask(pf, terms, nd: int) -> np.ndarray:
    """Host-side any-of term-presence mask over ``nd`` docs — the single
    implementation behind both the cached device filter masks
    (DeviceSegment.filter_mask) and the plan compiler's CPU-side
    threshold validation (search/plan.py)."""
    mask = np.zeros(nd, bool)
    rows = []
    for t in terms:
        tid = pf.term_id(t)
        if tid >= 0:
            s = int(pf.term_block_start[tid])
            rows.append(np.arange(s, s + int(pf.term_block_count[tid]),
                                  dtype=np.int64))
    if rows:
        rows = np.concatenate(rows)
        d = pf.block_docids[rows].reshape(-1)
        tf = pf.block_tfs[rows].reshape(-1)
        ok = tf > 0.0
        mask[d[ok][d[ok] < nd]] = True
    return mask


class DevicePostings:
    """One field's postings on device, with the reserved zero block."""

    def __init__(self, pf, n_docs_padded: int, device=None):
        tb = pf.block_docids.shape[0]
        docids = np.concatenate(
            [pf.block_docids, np.zeros((1, BLOCK_SIZE), np.int32)], axis=0)
        tfs = np.concatenate(
            [pf.block_tfs, np.zeros((1, BLOCK_SIZE), np.float32)], axis=0)
        put = partial(jax.device_put, device=device)
        self.block_docids = put(docids)
        self.block_tfs = put(tfs)
        self.block_max_tf = put(np.concatenate([pf.block_max_tf, [0.0]]).astype(np.float32))
        self.block_min_len = put(np.concatenate([pf.block_min_len, [0.0]]).astype(np.float32))
        lens = np.zeros(n_docs_padded, np.float32)
        lens[: len(pf.field_lengths)] = pf.field_lengths
        avg = pf.avg_field_length
        lens[len(pf.field_lengths):] = avg  # padded docs: harmless norm
        self.doc_lens = put(lens)
        self.zero_block = tb  # index of the reserved all-zeros block
        self.avg_len = float(avg)
        # host-side lookup stays on the host (term dict is a CPU structure)
        self.term_block_start = pf.term_block_start
        self.term_block_count = pf.term_block_count
        self.doc_freq = pf.doc_freq
        self.host = pf

    def select_blocks(self, term_ids, weights) -> Tuple[np.ndarray, np.ndarray]:
        """Host-side: term ids + per-term weights -> padded (block ids,
        per-block weights) bucketed to a power of two."""
        ids = []
        ws = []
        for tid, w in zip(term_ids, weights):
            if tid < 0:
                continue
            start = int(self.term_block_start[tid])
            count = int(self.term_block_count[tid])
            ids.extend(range(start, start + count))
            ws.extend([w] * count)
        n = block_bucket(max(1, len(ids)))
        pad = n - len(ids)
        ids.extend([self.zero_block] * pad)
        ws.extend([0.0] * pad)
        return np.asarray(ids, np.int32), np.asarray(ws, np.float32)


class DeviceVectors:
    def __init__(self, vv, n_docs_padded: int, dtype=jnp.bfloat16, device=None):
        prepped, norms = prepare_vectors(vv.vectors, vv.similarity, dtype)
        nd, d = prepped.shape
        if n_docs_padded > nd:
            prepped = np.concatenate(
                [prepped, np.zeros((n_docs_padded - nd, d), prepped.dtype)], axis=0)
            norms = np.concatenate([norms, np.zeros(n_docs_padded - nd, np.float32)])
        put = partial(jax.device_put, device=device)
        self.vectors = put(prepped)
        self.norms = put(norms)
        self.sq_norms = put((norms * norms).astype(np.float32))
        self.has_value = put(np.concatenate(
            [vv.has_value, np.zeros(n_docs_padded - nd, bool)]))
        self.similarity = vv.similarity
        self.dims = vv.dims


class DeviceSegment:
    """A segment resident in device HBM. Built once per (segment, device);
    refresh swaps whole DeviceSegments (epoch pointer swap, SURVEY.md §7
    stage 4)."""

    def __init__(self, segment: Segment, device=None, vector_dtype=jnp.bfloat16):
        self.segment = segment
        self.name = segment.name
        self.n_docs = segment.n_docs
        self.n_docs_padded = max(DOC_PAD, round_up(segment.n_docs, DOC_PAD))
        # packing invariant (ops/plan.py pack_result): docids ride
        # device→host readbacks as float32 casts, exact only < 2^24 —
        # enforce LOUDLY at build time, not as silent wraparound later
        from elasticsearch_tpu.ops.plan import check_packed_id_limit
        check_packed_id_limit(self.n_docs_padded,
                              f"DeviceSegment[{segment.name}]")
        self._device = device
        # backpressure sink (search/context.py DeviceSegmentCache):
        # filter-mask builds charge the hbm breaker through it; None
        # for standalone DeviceSegments outside a cache
        self.hbm_sink = None
        # LRU filter-mask cache — the analogue of Lucene's LRUQueryCache
        # for filter clauses (ref: search/LRUQueryCache.java via
        # IndicesQueryCache): an any-of terms filter caches as ONE dense
        # bool column, so its postings never enter the per-query sort.
        # Keyed by (field, terms); segment immutability (epoch swaps
        # replace whole DeviceSegments) keeps entries valid for the
        # segment's lifetime.
        self._filter_masks: "OrderedDict[tuple, tuple]" = OrderedDict()
        # BoundPlan cache (search/searcher.py): repeated queries reuse
        # their device-resident selection arrays — skipping bind_plan AND
        # the per-launch host→device uploads of the selections
        self._bound_plans: "OrderedDict[tuple, object]" = OrderedDict()
        # device-cache stats (engine observability — the analogue of
        # IndicesQueryCache stats): plain ints, advisory counters on a
        # GIL'd hot path. Bound-plan counters are incremented by the
        # searcher (the cache's only reader/writer).
        self.filter_mask_hits = 0
        self.filter_mask_misses = 0
        self.filter_mask_evictions = 0
        self.bound_plan_hits = 0
        self.bound_plan_misses = 0
        self.bound_plan_evictions = 0
        live = np.zeros(self.n_docs_padded, bool)
        live[: segment.n_docs] = segment.live
        self.live = jax.device_put(live, device=device)
        self.postings: Dict[str, DevicePostings] = {
            f: DevicePostings(pf, self.n_docs_padded, device)
            for f, pf in segment.postings.items()
        }
        self.vectors: Dict[str, DeviceVectors] = {
            f: DeviceVectors(vv, self.n_docs_padded, vector_dtype, device)
            for f, vv in segment.vectors.items()
        }
        # numeric doc values as dense device columns (range filters, sorts,
        # script features)
        put = partial(jax.device_put, device=device)
        self.numerics: Dict[str, jax.Array] = {}
        self.numeric_missing: Dict[str, jax.Array] = {}
        for f, nv in segment.numerics.items():
            vals = np.zeros(self.n_docs_padded, np.float64)
            vals[: len(nv.values)] = np.nan_to_num(nv.values, nan=0.0)
            miss = np.ones(self.n_docs_padded, bool)
            miss[: len(nv.missing)] = nv.missing
            self.numerics[f] = put(vals.astype(np.float32))
            self.numeric_missing[f] = put(miss)

    def keyword_ord_major(self, field: str):
        """(device docid-permutation int32 [total], host term_starts
        int64 [n_terms+1]) — every keyword value position sorted by ord,
        the ord-major layout the device terms-agg collector reduces over
        (ops/aggs.py). Built lazily once per immutable segment; None
        when the field has no keyword values."""
        cache = getattr(self, "_kw_ord_major", None)
        if cache is None:
            cache = self._kw_ord_major = {}
        if field in cache:
            return cache[field]
        kv = self.segment.keywords.get(field)
        if kv is None or len(kv.all_ords) == 0:
            cache[field] = None
            return None
        order = np.argsort(kv.all_ords, kind="stable")
        pos_doc = np.searchsorted(kv.offsets,
                                  np.arange(len(kv.all_ords)),
                                  side="right") - 1
        perm_docs = pos_doc[order].astype(np.int32)
        sorted_ords = kv.all_ords[order]
        term_starts = np.searchsorted(
            sorted_ords, np.arange(len(kv.terms) + 1)).astype(np.int64)
        entry = (jax.device_put(perm_docs, device=self._device),
                 term_starts)
        cache[field] = entry
        return entry

    def filter_mask(self, field: str, terms) -> Tuple[jax.Array, np.ndarray]:
        """Any-of terms-presence mask for ``field``, LRU-cached.

        Returns ``(device_mask, host_mask)`` — bool [n_docs_padded]. Built
        host-side from the segment's block postings (a pure gather — no
        device work) and uploaded once; subsequent queries reuse the
        column. The host copy stays available so the plan compiler can
        validate pruning thresholds CPU-side (search/plan.py).
        ref: Lucene LRUQueryCache — cached filters become bitsets that
        skip per-query scoring entirely."""
        key = (field, tuple(sorted(set(terms))))
        hit = self._filter_masks.get(key)
        if hit is not None:
            self.filter_mask_hits += 1
            self._filter_masks.move_to_end(key)
            return hit
        self.filter_mask_misses += 1
        dp = self.postings.get(field)
        if dp is not None:
            mask = host_any_mask(dp.host, key[1], self.n_docs_padded)
        else:
            mask = np.zeros(self.n_docs_padded, bool)
        # hbm admission BEFORE the device upload (the host mask has the
        # same nbytes) — a trip here surfaces as a typed per-shard
        # circuit_breaking_exception the coordinator fails over, and
        # nothing lands in device memory past the limit
        self._account_mask(int(mask.nbytes))
        dev_mask = jax.device_put(mask, device=self._device)
        entry = (dev_mask, mask)
        self._filter_masks[key] = entry
        while len(self._filter_masks) > FILTER_MASK_CACHE_MAX:
            _k, (evicted, _h) = self._filter_masks.popitem(last=False)
            self.filter_mask_evictions += 1
            self._account_mask(-int(evicted.nbytes))
        return entry

    def composed_filter_mask(self, conversions) -> Tuple[jax.Array,
                                                         np.ndarray]:
        """AND-composition of cached filter masks for a whole filter SET
        (``conversions``: [(field, terms, negate)]), itself cached. The
        returned DEVICE object is identical for every query using the
        same filters — the batcher keys cohorts on that identity, so one
        [ND] column serves a whole batched launch."""
        key = ("composed", tuple(
            (f, tuple(sorted(set(t))), bool(neg))
            for f, t, neg in sorted(conversions,
                                    key=lambda c: (c[0], c[1], c[2]))))
        hit = self._filter_masks.get(key)
        if hit is not None:
            self.filter_mask_hits += 1
            self._filter_masks.move_to_end(key)
            return hit
        self.filter_mask_misses += 1
        host = None
        for fname, terms, negate in key[1]:
            _, hm = self.filter_mask(fname, terms)
            hm = ~hm if negate else hm
            host = hm.copy() if host is None else (host & hm)
        self._account_mask(int(host.nbytes))
        dev_mask = jax.device_put(host, device=self._device)
        entry = (dev_mask, host)
        self._filter_masks[key] = entry
        while len(self._filter_masks) > FILTER_MASK_CACHE_MAX:
            _k, (evicted, _h) = self._filter_masks.popitem(last=False)
            self.filter_mask_evictions += 1
            self._account_mask(-int(evicted.nbytes))
        return entry

    def _account_mask(self, delta: int) -> None:
        """Charge/release device filter-mask bytes against the owning
        cache's hbm breaker (no-op for standalone segments)."""
        sink = self.hbm_sink
        if sink is not None:
            sink.account_filter_mask(self.name, delta)

    def update_live(self, live: np.ndarray) -> None:
        """Re-upload only the live mask (deletes don't touch postings)."""
        padded = np.zeros(self.n_docs_padded, bool)
        padded[: len(live)] = live
        self.live = jax.device_put(padded, device=self.live.devices().pop()
                                   if hasattr(self.live, "devices") else None)

    def hbm_bytes_by_class(self) -> Dict[str, int]:
        """Device-resident bytes per slab class (HBM_SLAB_CLASSES) —
        the engine-stats accounting model. ``postings`` is the block
        arrays + block-max metadata; ``norms`` the per-doc field-length
        columns (the analogue of Lucene's norms); ``doc_values`` the
        numeric columns + their missing masks; ``ordinals`` the lazy
        keyword ord-major permutations; ``filter_masks`` the LRU-cached
        device filter columns (so eviction visibly RETURNS bytes)."""
        out = dict.fromkeys(HBM_SLAB_CLASSES, 0)
        out["live_mask"] = int(self.live.nbytes)
        for dp in self.postings.values():
            out["postings"] += int(dp.block_docids.nbytes +
                                   dp.block_tfs.nbytes +
                                   dp.block_max_tf.nbytes +
                                   dp.block_min_len.nbytes)
            out["norms"] += int(dp.doc_lens.nbytes)
        for dv in self.vectors.values():
            out["vectors"] += int(dv.vectors.nbytes + dv.norms.nbytes +
                                  dv.sq_norms.nbytes +
                                  dv.has_value.nbytes)
        for arr in self.numerics.values():
            out["doc_values"] += int(arr.nbytes)
        for arr in self.numeric_missing.values():
            out["doc_values"] += int(arr.nbytes)
        for entry in (getattr(self, "_kw_ord_major", None) or {}).values():
            if entry is not None:
                out["ordinals"] += int(entry[0].nbytes)
        for dev_mask, _host in self._filter_masks.values():
            out["filter_masks"] += int(dev_mask.nbytes)
        return out

    def hbm_bytes(self) -> int:
        """Total device-resident bytes — BY CONSTRUCTION the sum of
        ``hbm_bytes_by_class()`` (the node-stats invariant pinned in
        tests/test_engine_stats.py)."""
        return sum(self.hbm_bytes_by_class().values())

    def cache_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-segment device-cache counters (engine observability —
        ref: IndicesQueryCache / LRUQueryCache stats)."""
        fm_bytes = sum(int(m.nbytes) for m, _h in
                       self._filter_masks.values())
        return {
            "filter_mask": {
                "hits": self.filter_mask_hits,
                "misses": self.filter_mask_misses,
                "evictions": self.filter_mask_evictions,
                "entries": len(self._filter_masks),
                "bytes": fm_bytes,
            },
            "bound_plan": {
                "hits": self.bound_plan_hits,
                "misses": self.bound_plan_misses,
                "evictions": self.bound_plan_evictions,
                "entries": len(self._bound_plans),
            },
        }
