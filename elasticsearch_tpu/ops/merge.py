"""Linear-work merge of per-term docid-sorted posting runs.

THE serving-kernel hot loop (round-4 headline, VERDICT r3 item 1): the
cohort kernel used to drag all P selected postings through one
monolithic ``lax.sort`` — O(P·logP) comparator stages against the CPU
baseline's O(P) DAAT merge (ref: Lucene MaxScoreBulkScorer's postings
merge, server/.../search/query/TopDocsCollectorContext.java:210-217).
Per-term postings are ALREADY docid-sorted on device, so sorting from
scratch throws that structure away.

This module merges T̂ sorted runs with log2(T̂) bitonic-merge rounds:

- strides >= CH run as XLA reshape compare-exchanges (contiguous
  chunks, bandwidth-efficient);
- strides < CH run inside ONE Pallas kernel per round: each grid
  program sorts a CH-sized bitonic chunk entirely in VMEM (bitonic
  stages only exchange within 2s-aligned groups, so CH-aligned chunks
  never interact once s < CH).

Reversals are avoided (Mosaic has no ``rev``) with the classic
alternating-direction invariant: run j is ascending for even j,
descending for odd j; the caller pre-flips odd input slots once, and
every round's compare directions follow pair parity.

Measured on the v5e (degraded-tunnel regime, [32, 2^19] i32+f32):
merge 156 ms/q vs lax.sort 461 ms/q — 3.0x; compile ~22s for all four
round kernels vs a single fused whole-merge pallas kernel which is
compile-pathological (>40 min, VMEM-OOM at the last round).

Compile observability: ``merge_sorted_slots`` is trace-time composable
(always called under an outer jit), so its per-shape compiles — the
~22s round-kernel builds above — are attributed to the CALLING kernel's
entry in the compile tracker (telemetry/engine.py `GET /_kernels`), not
to a row of their own.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
DEFAULT_CHUNK = 1 << 17


def _interpret() -> bool:
    """Pallas interpreter on CPU (tests); compiled Mosaic on TPU."""
    return jax.devices()[0].platform != "tpu"


def _iota(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _const(shape, v, dt=jnp.int32):
    return jax.lax.full(shape, v, dt)


def _chunk_kernel(k_ref, v_ref, ko_ref, vo_ref, *, ch, n, s0):
    """Bitonic stages s0 .. 1 on one CH-chunk in VMEM. Pair direction
    (ascending for even pair index, pair = global_flat_index // n)
    varies within the chunk when n < CH. Raw lax ops + bool algebra
    throughout — jnp operator promotion recurses in the kernel tracer,
    and Mosaic cannot lower a select BETWEEN bool operands."""
    cid = pl.program_id(1).astype(jnp.int32)
    R = ch // LANES
    k = k_ref[...].reshape(R, LANES)
    v = v_ref[...].reshape(R, LANES)

    def desc_rows(g_rows, rows_per_unit):
        base = jax.lax.mul(cid, np.int32(ch // LANES))
        i = _iota((g_rows, 1), 0)
        row0 = jax.lax.add(
            jax.lax.mul(i, _const((g_rows, 1), rows_per_unit)),
            jax.lax.broadcast(base, (g_rows, 1)))
        pair = jax.lax.div(row0, _const((g_rows, 1), n // LANES))
        return jax.lax.eq(jax.lax.rem(pair, _const((g_rows, 1), 2)),
                          _const((g_rows, 1), 1))

    s = s0
    while s >= LANES:
        sr = s // LANES
        g = R // (2 * sr)
        kr = k.reshape(g, 2, sr, LANES)
        vr = v.reshape(g, 2, sr, LANES)
        lo_k, hi_k = kr[:, 0], kr[:, 1]
        lo_v, hi_v = vr[:, 0], vr[:, 1]
        desc = desc_rows(g, 2 * sr).reshape(g, 1, 1)
        sw = jax.lax.bitwise_xor(jax.lax.gt(lo_k, hi_k), desc)
        nk = jnp.stack([jnp.where(sw, hi_k, lo_k),
                        jnp.where(sw, lo_k, hi_k)], axis=1)
        nv = jnp.stack([jnp.where(sw, hi_v, lo_v),
                        jnp.where(sw, lo_v, hi_v)], axis=1)
        k = nk.reshape(R, LANES)
        v = nv.reshape(R, LANES)
        s //= 2
    dr = desc_rows(R, 1)
    while s >= 1:
        ku = pltpu.roll(k, np.int32(LANES - s), 1)   # lane l <- l+s
        kd = pltpu.roll(k, np.int32(s), 1)           # lane l <- l-s
        vu = pltpu.roll(v, np.int32(LANES - s), 1)
        vd = pltpu.roll(v, np.int32(s), 1)
        lane = _iota((R, LANES), 1)
        is_lo = jax.lax.eq(
            jax.lax.rem(jax.lax.div(lane, _const((R, LANES), s)),
                        _const((R, LANES), 2)),
            _const((R, LANES), 0))
        pk = jnp.where(is_lo, ku, kd)
        pv = jnp.where(is_lo, vu, vd)
        take = jax.lax.bitwise_or(
            jax.lax.bitwise_and(is_lo, jax.lax.lt(pk, k)),
            jax.lax.bitwise_and(jax.lax.bitwise_not(is_lo),
                                jax.lax.gt(pk, k)))
        take = jax.lax.bitwise_xor(take, dr)
        k = jnp.where(take, pk, k)
        v = jnp.where(take, pv, v)
        s //= 2
    ko_ref[...] = k.reshape(ko_ref.shape)
    vo_ref[...] = v.reshape(vo_ref.shape)


def _chunk_call(Q, P, ch, n, s0, val_dtype):
    nch = P // ch
    rows = ch // LANES
    kfn = functools.partial(_chunk_kernel, ch=ch, n=n, s0=s0)
    zero = np.int32(0)

    def f(k, v):
        k4 = k.reshape(Q, nch, rows, LANES)
        v4 = v.reshape(Q, nch, rows, LANES)
        ko, vo = pl.pallas_call(
            kfn,
            grid=(Q, nch),
            in_specs=[
                pl.BlockSpec((1, 1, rows, LANES),
                             lambda q, c: (q, c, zero, zero),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, rows, LANES),
                             lambda q, c: (q, c, zero, zero),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, rows, LANES),
                             lambda q, c: (q, c, zero, zero),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1, rows, LANES),
                             lambda q, c: (q, c, zero, zero),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Q, nch, rows, LANES), jnp.int32),
                jax.ShapeDtypeStruct((Q, nch, rows, LANES), val_dtype),
            ],
            interpret=_interpret(),
        )(k4, v4)
        return ko.reshape(Q, P), vo.reshape(Q, P)
    return f


def _xla_stage(k, v, s, n, Q, P):
    """Compare-exchange at stride s (>= chunk) with pair-parity
    directions — contiguous chunk reshapes, plain XLA."""
    g = P // (2 * s)
    kr = k.reshape(Q, g, 2, s)
    vr = v.reshape(Q, g, 2, s)
    lo_k, hi_k = kr[:, :, 0], kr[:, :, 1]
    lo_v, hi_v = vr[:, :, 0], vr[:, :, 1]
    pair = (jnp.arange(g, dtype=jnp.int32) * 2 * s) // n
    desc = ((pair % 2) == 1)[None, :, None]
    sw = (lo_k > hi_k) != desc
    nk = jnp.stack([jnp.where(sw, hi_k, lo_k),
                    jnp.where(sw, lo_k, hi_k)], axis=2)
    nv = jnp.stack([jnp.where(sw, hi_v, lo_v),
                    jnp.where(sw, lo_v, hi_v)], axis=2)
    return nk.reshape(Q, P), nv.reshape(Q, P)


def merge_sorted_slots(keys, vals, chunk: int = DEFAULT_CHUNK,
                       force_pallas: bool = False):
    """Merge [Q, n_slots, L] (each slot ascending by key; sentinel
    padding sorts last) → ([Q, P], [Q, P]) globally ascending. n_slots
    must be a power of two; slot length L a multiple of 128.

    Trace-time composable (call under jit); the per-round pallas calls
    compile once per (Q, P, chunk, n) shape.

    Off-TPU (CPU tests) the postcondition is produced by a plain
    ``lax.sort`` — the pallas interpreter is orders slower and the
    network itself is covered by tests/test_merge.py via
    ``force_pallas``."""
    Q, n_slots, L = keys.shape
    P = n_slots * L
    if _interpret() and not force_pallas:
        return jax.lax.sort((keys.reshape(Q, P), vals.reshape(Q, P)),
                            dimension=1, num_keys=1)
    ch = min(chunk, P)
    # odd slots become descending (alternating-direction invariant)
    k = keys.at[:, 1::2].set(keys[:, 1::2, ::-1])
    v = vals.at[:, 1::2].set(vals[:, 1::2, ::-1])
    k = k.reshape(Q, P)
    v = v.reshape(Q, P)
    ns, ln = n_slots, L
    while ns > 1:
        n = 2 * ln
        s = n // 2
        while s >= ch:
            k, v = _xla_stage(k, v, s, n, Q, P)
            s //= 2
        k, v = _chunk_call(Q, P, ch, n, min(n, ch) // 2,
                           vals.dtype)(k, v)
        ns //= 2
        ln = n
    return k, v
