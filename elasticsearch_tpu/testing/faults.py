"""Seeded fault injection for the transport layer.

`FaultInjectingTransport` wraps any transport exposing the
TransportService surface (`send_request`, `register_request_handler`,
`local_node`, ...) and applies per-(action, node) fault rules to
OUTBOUND requests: error them, drop them fast, black-hole them (vanish
until the caller's timeout), or delay them. All randomness — rule
probability draws and delay jitter — comes from ONE seeded RNG shared
through a `FaultInjector`, and delays/timeouts are scheduled on the
provided `Scheduler`, so composing with `DeterministicTaskQueue` makes
every chaos run replayable from its seed (ref: the reference's
DisruptableMockTransport + RandomizedRunner seed discipline).

Usage (deterministic harness):

    queue = DeterministicTaskQueue(seed=7)
    injector = FaultInjector(seed=7, scheduler=queue)
    transport = FaultInjectingTransport(
        DisruptableTransport(node, network), injector)
    injector.add_rule(FaultRule(action="phase/query", node="dn-1",
                                mode=ERROR))

The injector keeps a log of every injected fault, so tests can assert
that chaos actually happened and echo the seed for replay.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

# fault modes
ERROR = "error"            # immediate remote-style failure
DISCONNECT = "disconnect"  # fast connection-refused failure
BLACKHOLE = "blackhole"    # request vanishes; only the timeout answers
DELAY = "delay"            # request delivered late (seeded jitter)

MODES = (ERROR, DISCONNECT, BLACKHOLE, DELAY)


class InjectedFaultError(ConnectionError):
    """Default error raised by ERROR-mode rules (a ConnectionError
    subclass, so failover classifies it retryable)."""

    def __init__(self, action: str, node: str, seed_note: str = ""):
        super().__init__(
            f"[faults] injected failure for [{action}] -> [{node}]"
            + (f" ({seed_note})" if seed_note else ""))


class FaultRule:
    """One (action, node) fault rule. `action` is a substring match,
    `node` an EXACT node-id match ('dn-1' must not also hit 'dn-10');
    None matches everything. `probability` is drawn per send from the
    injector's seeded RNG; `times` bounds how often the rule fires
    (None = unlimited); DELAY mode draws a delay uniformly from `delay`
    (a (min, max) pair or a constant)."""

    def __init__(self, action: Optional[str] = None,
                 node: Optional[str] = None, mode: str = ERROR,
                 probability: float = 1.0,
                 times: Optional[int] = None,
                 delay: Any = 0.5,
                 error_factory: Optional[Callable[[str, str],
                                                  BaseException]] = None):
        if mode not in MODES:
            raise ValueError(f"unknown fault mode [{mode}]")
        self.action = action
        self.node = node
        self.mode = mode
        self.probability = probability
        self.remaining = times
        self.delay = delay if isinstance(delay, tuple) else (delay, delay)
        self.error_factory = error_factory

    def matches(self, action: str, node_id: str) -> bool:
        if self.remaining is not None and self.remaining <= 0:
            return False
        if self.action is not None and self.action not in action:
            return False
        if self.node is not None and self.node != node_id:
            return False
        return True


class FaultInjector:
    """Shared seeded decision-maker: every wrapped transport asks it
    whether (and how) to disturb a send. One RNG + one scheduler per
    cluster keeps the whole chaos schedule a pure function of the
    seed (given the DeterministicTaskQueue's execution order)."""

    def __init__(self, seed: int = 0, scheduler=None):
        self.seed = seed
        self.random = random.Random(seed)
        self.scheduler = scheduler
        self.rules: List[FaultRule] = []
        self.injected: List[Tuple[str, str, str]] = []  # (action, node, mode)
        self.sends: Dict[str, int] = {}                 # action -> count

    def add_rule(self, rule: FaultRule) -> FaultRule:
        self.rules.append(rule)
        return rule

    def clear_rules(self) -> None:
        self.rules.clear()

    def record_send(self, action: str) -> None:
        self.sends[action] = self.sends.get(action, 0) + 1

    def send_count(self, action_substr: str) -> int:
        return sum(n for a, n in self.sends.items() if action_substr in a)

    def injected_count(self, action_substr: str = "",
                       node: str = "") -> int:
        return sum(1 for a, n, _m in self.injected
                   if action_substr in a and (not node or n == node))

    def decide(self, action: str, node_id: str) -> Optional[FaultRule]:
        for rule in self.rules:
            if not rule.matches(action, node_id):
                continue
            if rule.probability < 1.0 and \
                    self.random.random() >= rule.probability:
                continue
            if rule.remaining is not None:
                rule.remaining -= 1
            self.injected.append((action, node_id, rule.mode))
            return rule
        return None

    def draw_delay(self, rule: FaultRule) -> float:
        lo, hi = rule.delay
        return lo if lo >= hi else self.random.uniform(lo, hi)


class FaultInjectingTransport:
    """Transport wrapper applying the injector's rules to outbound
    `send_request` calls. Everything else delegates to the wrapped
    transport, so it drops in anywhere a TransportService or
    DisruptableTransport does (ClusterNode takes it unchanged)."""

    def __init__(self, inner, injector: FaultInjector, scheduler=None):
        self.inner = inner
        self.injector = injector
        self.scheduler = scheduler or injector.scheduler
        if self.scheduler is None:
            raise ValueError(
                "FaultInjectingTransport needs a scheduler (pass one "
                "here or on the FaultInjector)")

    # -- delegated surface -----------------------------------------------

    @property
    def local_node(self):
        return self.inner.local_node

    def register_request_handler(self, action: str, handler: Callable,
                                 executor: str = "generic",
                                 can_trip_breaker: bool = True) -> None:
        self.inner.register_request_handler(
            action, handler, executor, can_trip_breaker=can_trip_breaker)

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # -- the fault seam ---------------------------------------------------

    def send_request(self, node, action: str, request: Any, handler,
                     timeout: Optional[float] = None,
                     headers: Optional[Dict] = None) -> None:
        inj = self.injector
        inj.record_send(action)
        rule = inj.decide(action, node.node_id)
        if rule is None:
            self.inner.send_request(node, action, request, handler,
                                    timeout=timeout, headers=headers)
            return
        sched = self.scheduler
        if rule.mode == ERROR:
            exc = (rule.error_factory(action, node.node_id)
                   if rule.error_factory else
                   InjectedFaultError(action, node.node_id,
                                      f"seed={inj.seed}"))
            sched.schedule(0.0, lambda: handler.on_failure(exc),
                           f"fault-error {action}->{node.name}")
        elif rule.mode == DISCONNECT:
            sched.schedule(
                0.0, lambda: handler.on_failure(ConnectionError(
                    f"[faults] [{node.name}] disconnected "
                    f"(seed={inj.seed})")),
                f"fault-disconnect {action}->{node.name}")
        elif rule.mode == BLACKHOLE:
            # vanishes; the caller's timeout is the only way out
            if timeout is not None:
                sched.schedule(
                    timeout, lambda: handler.on_failure(TimeoutError(
                        f"[faults] [{node.name}][{action}] black-holed "
                        f"(seed={inj.seed})")),
                    f"fault-blackhole {action}->{node.name}")
        elif rule.mode == DELAY:
            delay = inj.draw_delay(rule)
            sched.schedule(
                delay,
                lambda: self.inner.send_request(node, action, request,
                                                handler, timeout=timeout,
                                                headers=headers),
                f"fault-delay {action}->{node.name}")


class MemoryPressureFault:
    """Seeded memory-pressure injection: shrink a node's circuit-breaker
    and indexing-pressure limits MID-FLIGHT (and optionally restore them
    later), on the shared scheduler so the squeeze lands at a
    deterministic virtual time. Models a neighbour tenant ballooning, a
    fragmentation spike, or an operator tightening
    ``indices.breaker.*.limit`` under load — the system must shed
    (partial results, 429s), never crash or hang.

    ``apply()`` fires immediately; ``schedule(delay)`` defers the
    squeeze by ``delay`` (virtual) seconds from now; ``restore()`` puts
    the original limits back (retried bulks succeed after release — the
    recovery half of the backpressure contract).
    """

    def __init__(self, breaker_service=None, indexing_pressure=None,
                 factor: float = 0.0, floor_bytes: int = 0):
        self.breaker_service = breaker_service
        self.indexing_pressure = indexing_pressure
        self.factor = factor
        self.floor_bytes = floor_bytes
        self._saved: Optional[Dict[str, int]] = None
        self._saved_pressure: Optional[int] = None

    def apply(self) -> None:
        svc = self.breaker_service
        if svc is not None and self._saved is None:
            self._saved = {name: svc.get_breaker(name).limit
                           for name in svc.breaker_names()}
            self._saved["__parent__"] = svc.total_limit
            for name in svc.breaker_names():
                br = svc.get_breaker(name)
                br.set_limit(max(self.floor_bytes,
                                 int(br.limit * self.factor)))
            svc.total_limit = max(self.floor_bytes,
                                  int(svc.total_limit * self.factor))
        ip = self.indexing_pressure
        if ip is not None and self._saved_pressure is None:
            self._saved_pressure = ip.limit
            ip.limit = max(self.floor_bytes, int(ip.limit * self.factor))

    def restore(self) -> None:
        svc = self.breaker_service
        if svc is not None and self._saved is not None:
            svc.total_limit = self._saved.pop("__parent__")
            for name, limit in self._saved.items():
                svc.get_breaker(name).set_limit(limit)
            self._saved = None
        ip = self.indexing_pressure
        if ip is not None and self._saved_pressure is not None:
            ip.limit = self._saved_pressure
            self._saved_pressure = None

    def schedule(self, scheduler, delay: float,
                 restore_after: Optional[float] = None) -> None:
        """Squeeze ``delay`` seconds from now (scheduler delays are
        RELATIVE); restore ``restore_after`` seconds after that."""
        scheduler.schedule(delay, self.apply, "fault-memory-pressure")
        if restore_after is not None:
            scheduler.schedule(delay + restore_after, self.restore,
                               "fault-memory-pressure-restore")
