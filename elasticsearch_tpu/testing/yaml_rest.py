"""Declarative YAML REST test runner.

Mirrors the reference's YAML REST suite machinery (ref: test/framework/
.../test/rest/yaml/ESClientYamlSuiteTestCase — SURVEY.md §4 tier 5: the
same declarative do/match suites run against every distribution).
Re-design for this engine: suites execute against the in-process
RestController (no sockets needed — the controller is transport-agnostic
by design), with the reference's assertion vocabulary:

  - do:        run an API call. Either an api shorthand
                 (`search: {index: i, body: {...}}`) or
                 `raw: {method, path, params, body}`.
  - match:     dot-path equality against the last response
  - length:    dot-path collection length
  - is_true / is_false / gt / gte / lt / lte
  - set:       capture a response value into a variable ($var)

Each test in a file runs against a fresh node unless the file declares
`setup:` steps (run once per test, like the reference's per-test setup).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import yaml

# api-name shorthands → (method, path template). {x} fills from the call
# body's top-level keys; remaining keys become params/body.
_APIS = {
    "indices.create": ("PUT", "/{index}"),
    "indices.delete": ("DELETE", "/{index}"),
    "indices.refresh": ("POST", "/{index}/_refresh"),
    "indices.get_mapping": ("GET", "/{index}/_mapping"),
    "indices.put_mapping": ("PUT", "/{index}/_mapping"),
    "indices.get_settings": ("GET", "/{index}/_settings"),
    "indices.close": ("POST", "/{index}/_close"),
    "indices.open": ("POST", "/{index}/_open"),
    "indices.stats": ("GET", "/{index}/_stats"),
    "index": ("PUT", "/{index}/_doc/{id}"),
    "create": ("PUT", "/{index}/_create/{id}"),
    "get": ("GET", "/{index}/_doc/{id}"),
    "delete": ("DELETE", "/{index}/_doc/{id}"),
    "update": ("POST", "/{index}/_update/{id}"),
    "search": ("POST", "/{index}/_search"),
    "count": ("POST", "/{index}/_count"),
    "bulk": ("POST", "/_bulk"),
    "mget": ("POST", "/{index}/_mget"),
    "cluster.health": ("GET", "/_cluster/health"),
    "cat.indices": ("GET", "/_cat/indices"),
    "ingest.put_pipeline": ("PUT", "/_ingest/pipeline/{id}"),
    "ingest.simulate": ("POST", "/_ingest/pipeline/_simulate"),
    "sql.query": ("POST", "/_sql"),
    "eql.search": ("POST", "/{index}/_eql/search"),
    "ml.put_job": ("PUT", "/_ml/anomaly_detectors/{id}"),
    "watcher.put_watch": ("PUT", "/_watcher/watch/{id}"),
    "rank_eval": ("POST", "/{index}/_rank_eval"),
}


class YamlTestFailure(AssertionError):
    pass


def _resolve_path(obj: Any, path: str):
    """`hits.hits.0._source.title` style dot path; $body = whole
    response. A `*` segment traverses a SINGLE-entry dict regardless of
    its key (e.g. `nodes.*.telemetry` — node ids are random per run,
    mirroring the reference runner's $node_id stashing)."""
    if path in ("$body", ""):
        return obj
    cur = obj
    # ES YAML escapes literal dots in keys as "a\.b"
    for raw in re.split(r"(?<!\\)\.", path):
        part = raw.strip().replace("\\.", ".")
        if isinstance(cur, dict):
            if part == "*" and part not in cur:
                if len(cur) != 1:
                    raise YamlTestFailure(
                        f"path [{path}]: [*] needs exactly one key, "
                        f"got {len(cur)}")
                cur = next(iter(cur.values()))
                continue
            if part not in cur:
                raise YamlTestFailure(f"path [{path}]: missing [{part}]")
            cur = cur[part]
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                raise YamlTestFailure(f"path [{path}]: bad index [{part}]")
        else:
            raise YamlTestFailure(f"path [{path}]: hit a leaf at [{part}]")
    return cur


class YamlRestRunner:
    """`node_factory` yields a fresh node per test (the reference wipes
    cluster state between YAML tests)."""

    def __init__(self, node_factory):
        self.node_factory = node_factory
        self.node = None
        self.last_response: Any = None
        self.last_status: int = 0
        self.vars: Dict[str, Any] = {}

    # ----------------------------------------------------------- running
    def run_file(self, path: str):
        with open(path) as fh:
            docs = list(yaml.safe_load_all(fh))
        suite: Dict[str, List] = {}
        for doc in docs:
            if doc:
                suite.update(doc)
        setup = suite.pop("setup", None)
        suite.pop("teardown", None)
        for test_name, steps in suite.items():
            self.node = self.node_factory()
            self.vars = {}
            try:
                if setup:
                    self._run_steps(setup, f"{path}::setup")
                self._run_steps(steps, f"{path}::{test_name}")
            finally:
                self.node.close()
                self.node = None

    def _run_steps(self, steps: List[Dict[str, Any]], where: str):
        for step in steps:
            (kind, body), = step.items()
            try:
                self._step(kind, body)
            except YamlTestFailure as e:
                raise YamlTestFailure(f"{where}: {e}") from None

    # ------------------------------------------------------------- steps
    def _step(self, kind: str, body: Any):
        if kind == "do":
            self._do(body)
        elif kind == "match":
            (path, expected), = body.items()
            actual = _resolve_path(self.last_response,
                                   self._subst(path))
            expected = self._subst(expected)
            if isinstance(expected, str) and expected.startswith("/") \
                    and expected.endswith("/"):
                if re.search(expected.strip("/"), str(actual)) is None:
                    raise YamlTestFailure(
                        f"match {path}: [{actual}] !~ {expected}")
            elif actual != expected:
                raise YamlTestFailure(
                    f"match {path}: [{actual!r}] != [{expected!r}]")
        elif kind == "length":
            (path, expected), = body.items()
            actual = _resolve_path(self.last_response, self._subst(path))
            expected = self._subst(expected)
            if len(actual) != expected:
                raise YamlTestFailure(
                    f"length {path}: {len(actual)} != {expected}")
        elif kind in ("is_true", "is_false"):
            try:
                v = _resolve_path(self.last_response, self._subst(body))
            except YamlTestFailure:
                # ES runner semantics: a missing path is simply falsy
                v = None
            truthy = bool(v) and v not in ("false",)
            if truthy != (kind == "is_true"):
                raise YamlTestFailure(f"{kind} {body}: got [{v!r}]")
        elif kind in ("gt", "gte", "lt", "lte"):
            (path, expected), = body.items()
            expected = self._subst(expected)
            actual = _resolve_path(self.last_response, self._subst(path))
            ok = {"gt": actual > expected, "gte": actual >= expected,
                  "lt": actual < expected, "lte": actual <= expected}[kind]
            if not ok:
                raise YamlTestFailure(
                    f"{kind} {path}: {actual} vs {expected}")
        elif kind == "set":
            (path, var), = body.items()
            self.vars[var] = _resolve_path(self.last_response,
                                           self._subst(path))
        else:
            raise YamlTestFailure(f"unknown step [{kind}]")

    def _do(self, body: Dict[str, Any]):
        body = dict(body)
        catch = body.pop("catch", None)
        (api, spec), = body.items()
        spec = self._subst(spec) or {}
        if api == "raw":
            method = spec.get("method", "GET")
            path = spec.get("path", "/")
            params = spec.get("params", {}) or {}
            req_body = spec.get("body")
        elif api in _APIS:
            method, template = _APIS[api]
            spec = dict(spec)
            req_body = spec.pop("body", None)
            path = re.sub(r"{(\w+)}",
                          lambda m: str(spec.pop(m.group(1), "")),
                          template).rstrip("/")
            # index-less search etc: collapse double slashes
            path = re.sub(r"//+", "/", path) or "/"
            params = {k: str(v) for k, v in spec.items()}
        else:
            raise YamlTestFailure(f"unknown api [{api}]")
        status, resp = self.node.rest_controller.dispatch(
            method, path, params, req_body)
        self.last_status, self.last_response = status, resp
        if catch is not None:
            named = {"missing": 404, "conflict": 409,
                     "bad_request": 400, "forbidden": 403,
                     "unauthorized": 401, "param": 400}
            if status < 400:
                raise YamlTestFailure(
                    f"do[catch={catch}]: expected an error, got {status}")
            if catch in named:
                if status != named[catch]:
                    raise YamlTestFailure(
                        f"do[catch={catch}]: expected {named[catch]}, "
                        f"got {status}: {resp}")
            elif catch == "request":
                # ES semantics: any error not covered by the named ones
                pass
            elif (isinstance(catch, str) and catch.startswith("/")
                    and catch.endswith("/")):
                # regex catch checks the error body (ES /pattern/ form)
                import json as _json
                if re.search(catch.strip("/"), _json.dumps(resp)) is None:
                    raise YamlTestFailure(
                        f"do[catch={catch}]: error body does not match: "
                        f"{resp}")
            else:
                raise YamlTestFailure(f"unknown catch [{catch}]")
        elif status >= 400:
            raise YamlTestFailure(
                f"do[{api}]: HTTP {status}: {resp}")

    def _subst(self, value):
        """$var substitution anywhere in strings/containers."""
        if isinstance(value, str):
            for name, v in self.vars.items():
                if value == f"${name}":
                    return v
                value = value.replace(f"${name}", str(v))
            return value
        if isinstance(value, dict):
            return {self._subst(k): self._subst(v)
                    for k, v in value.items()}
        if isinstance(value, list):
            return [self._subst(v) for v in value]
        return value
