"""Deterministic distributed-simulation harness.

The framework's equivalent of the reference's crown-jewel test tier (ref:
SURVEY.md §4.3): `DeterministicTaskQueue` (virtual time + seeded task
interleaving), `DisruptableMockTransport` (drop/delay/partition messages
per link), and a `LinearizabilityChecker`. Multi-node coordination logic
runs single-threaded over virtual time, so every schedule is replayable
from its seed — the practical race detector for this layer (there is no
TSAN for distributed protocols).

Design: components that must run both in production and under simulation
depend only on the `Scheduler` protocol (now / schedule / execute) and a
transport exposing `send_request` / `register_request_handler` — the
production `TransportService` and the sim transport here are drop-in
replacements for each other.
"""

from __future__ import annotations

import heapq
import itertools
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.common.errors import CircuitBreakingException
from elasticsearch_tpu.telemetry import context as _telectx
from elasticsearch_tpu.transport.transport import (
    CURRENT_VERSION,
    DiscoveryNode,
    ResponseHandler,
    TransportChannel,
    charge_inflight,
    instrument_inbound,
    instrument_send,
)


class Scheduler:
    """Protocol: what coordination-layer components need from time."""

    def now(self) -> float:
        raise NotImplementedError

    def schedule(self, delay: float, fn: Callable[[], None],
                 description: str = "") -> "Cancellable":
        raise NotImplementedError

    def execute(self, fn: Callable[[], None], description: str = "") -> None:
        self.schedule(0.0, fn, description)


class Cancellable:
    def __init__(self) -> None:
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class ThreadedScheduler(Scheduler):
    """Production scheduler over a single timer thread."""

    def __init__(self) -> None:
        import threading
        self._cond = threading.Condition()
        self._queue: List[Tuple[float, int, Cancellable, Callable]] = []
        self._seq = itertools.count()
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="scheduler")
        self._thread.start()

    def now(self) -> float:
        import time
        return time.monotonic()

    def schedule(self, delay: float, fn: Callable[[], None],
                 description: str = "") -> Cancellable:
        c = Cancellable()
        # profile recorder + trace context are temporal: carry them with
        # the task so they are live when the timer thread runs it
        fn = _telectx.bind(fn)
        with self._cond:
            heapq.heappush(self._queue,
                           (self.now() + delay, next(self._seq), c, fn))
            self._cond.notify()
        return c

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                if not self._queue:
                    self._cond.wait(0.1)
                    continue
                when, _seq, c, fn = self._queue[0]
                wait = when - self.now()
                if wait > 0:
                    self._cond.wait(min(wait, 0.1))
                    continue
                heapq.heappop(self._queue)
            if not c.cancelled:
                try:
                    fn()
                except Exception:
                    import traceback
                    traceback.print_exc()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()


class DeterministicTaskQueue(Scheduler):
    """Virtual time + seeded execution order (ref:
    test/framework/.../DeterministicTaskQueue.java).

    Runnable tasks execute in random (seeded) order; deferred tasks become
    runnable when virtual time is advanced to their execution time.
    """

    def __init__(self, seed: int = 0):
        self.random = random.Random(seed)
        self._now = 0.0
        self._runnable: List[Tuple[str, Callable]] = []
        self._deferred: List[Tuple[float, int, Cancellable, str, Callable]] = []
        self._seq = itertools.count()

    # -- Scheduler --------------------------------------------------------

    def now(self) -> float:
        return self._now

    def schedule(self, delay: float, fn: Callable[[], None],
                 description: str = "") -> Cancellable:
        c = Cancellable()
        # carry the ambient profile recorder / stage sink / trace
        # context across the task boundary: a shard-side handler
        # scheduled here must record into the search's contexts even
        # though the installing scope has long exited (`profile: true`
        # on a multi-node search keeps its shard stages)
        fn = _telectx.bind(fn)
        if delay <= 0:
            self._runnable.append((description, self._guard(c, fn)))
        else:
            heapq.heappush(self._deferred,
                           (self._now + delay, next(self._seq), c,
                            description, fn))
        return c

    def _guard(self, c: Cancellable, fn: Callable) -> Callable:
        def run():
            if not c.cancelled:
                fn()
        return run

    # -- driving ----------------------------------------------------------

    def has_runnable(self) -> bool:
        return bool(self._runnable)

    def has_deferred(self) -> bool:
        return bool(self._deferred)

    def run_random_task(self) -> None:
        i = self.random.randrange(len(self._runnable))
        _desc, fn = self._runnable.pop(i)
        fn()

    def advance_time(self) -> None:
        """Jump virtual time to the next deferred task's time and make all
        tasks due at that time runnable."""
        if not self._deferred:
            return
        self._now = max(self._now, self._deferred[0][0])
        while self._deferred and self._deferred[0][0] <= self._now:
            _when, _seq, c, desc, fn = heapq.heappop(self._deferred)
            self._runnable.append((desc, self._guard(c, fn)))

    def run_all_runnable(self) -> int:
        n = 0
        while self._runnable:
            self.run_random_task()
            n += 1
        return n

    def run_until_idle(self, max_tasks: int = 100_000) -> None:
        """Run every task, advancing time as needed, until nothing is
        scheduled (only safe when the system quiesces, e.g. after
        stabilisation w/ recurring tasks cancelled)."""
        n = 0
        while self._runnable or self._deferred:
            if not self._runnable:
                self.advance_time()
            self.run_random_task()
            n += 1
            if n > max_tasks:
                raise AssertionError("task queue did not quiesce")

    def run_for(self, duration: float, max_tasks: int = 500_000) -> None:
        """Run tasks (in seeded-random order, advancing virtual time) for
        `duration` virtual seconds."""
        deadline = self._now + duration
        n = 0
        while True:
            if self._runnable:
                self.run_random_task()
                n += 1
                if n > max_tasks:
                    raise AssertionError("too many tasks within window")
            elif self._deferred and self._deferred[0][0] <= deadline:
                self.advance_time()
            else:
                break
        self._now = deadline


# ---------------------------------------------------------------- network

CONNECTED = "connected"
DISCONNECTED = "disconnected"   # sends fail fast (connection refused)
BLACKHOLE = "blackhole"         # sends vanish (partition without error)


class DisruptableTransport:
    """Per-node sim transport delivering through a shared
    DeterministicTaskQueue, with per-link disruption (ref:
    test/framework/.../DisruptableMockTransport.java).

    API-compatible subset of TransportService: `send_request`,
    `register_request_handler`, `local_node`, `connect_to_node`.
    """

    def __init__(self, local_node: DiscoveryNode, network: "SimNetwork"):
        self.local_node = local_node
        self.network = network
        self.telemetry = None
        # node breaker service: same inbound in_flight_requests seam as
        # the production BaseTransport, so chaos runs exercise shedding
        self.breaker_service = None
        # wire version this sim node speaks — rolling-upgrade tests pin
        # one node down a version and the negotiated minimum gates any
        # protocol feature (same seam as TcpTransport._peer_versions)
        self.wire_version = CURRENT_VERSION
        self._handlers: Dict[str, Callable] = {}
        self._no_trip: Set[str] = set()
        network.register(self)

    def negotiated_version(self, node_id: str) -> int:
        """Wire version agreed with a peer: min of both ends (the sim
        registry stands in for the TCP handshake)."""
        peer = self.network.transports.get(node_id)
        peer_version = getattr(peer, "wire_version", CURRENT_VERSION)
        return min(self.wire_version, peer_version)

    # -- TransportService surface ----------------------------------------

    def register_request_handler(self, action: str, handler: Callable,
                                 executor: str = "generic",
                                 can_trip_breaker: bool = True) -> None:
        self._handlers[action] = handler
        if not can_trip_breaker:
            self._no_trip.add(action)

    def connect_to_node(self, node: DiscoveryNode,
                        timeout: float = 5.0) -> None:
        if self.network.link_state(self.local_node, node) != CONNECTED:
            raise ConnectionError(f"cannot connect to {node.name}")

    def node_connected(self, node: DiscoveryNode) -> bool:
        return self.network.link_state(self.local_node, node) == CONNECTED

    def send_request(self, node: DiscoveryNode, action: str, request: Any,
                     handler: ResponseHandler,
                     timeout: Optional[float] = None,
                     headers: Optional[Dict[str, Any]] = None) -> None:
        # same send-side telemetry seam as the production transport
        request, handler = instrument_send(self.telemetry, action,
                                           request, handler, headers)
        self.network.deliver(self, node, action, request, handler, timeout)

    def send_request_sync(self, *a, **k):  # pragma: no cover
        raise AssertionError("sync sends are forbidden under simulation")

    # -- inbound ----------------------------------------------------------

    def handle(self, source: DiscoveryNode, action: str, request: Any,
               respond: Callable[[Any, bool], None]) -> None:
        handler = self._handlers.get(action)
        headers = instrument_inbound(self.telemetry, action, request)
        release_box: Dict[str, Callable] = {}

        def responding(payload: Any, is_error: bool) -> None:
            rel = release_box.pop("release", None)
            if rel is not None:
                rel()
            respond(payload, is_error)

        channel = TransportChannel(responding, action)
        if handler is None:
            channel.send_exception(
                KeyError(f"No handler for action [{action}]"))
            return
        if self.breaker_service is not None and \
                action not in self._no_trip:
            try:
                rel = charge_inflight(self.breaker_service, action,
                                      request)
                if rel is not None:
                    release_box["release"] = rel
            except CircuitBreakingException as e:
                channel.send_exception(e)
                return
        try:
            with _telectx.incoming(headers):
                handler(request, channel, source)
        except BaseException as e:  # noqa: BLE001 — sim fault barrier
            channel.send_exception(e)


class SimNetwork:
    """The shared medium: link states + message delivery as tasks.

    Request and response legs are separately subject to the link state at
    the moment each leg is delivered — exactly the reference semantics
    (DisruptableMockTransport delivers or drops each message when its
    task runs).
    """

    def __init__(self, queue: DeterministicTaskQueue,
                 min_delay: float = 0.001, max_delay: float = 0.05):
        self.queue = queue
        self.transports: Dict[str, DisruptableTransport] = {}
        self._links: Dict[Tuple[str, str], str] = {}
        self.min_delay = min_delay
        self.max_delay = max_delay

    def register(self, t: DisruptableTransport) -> None:
        self.transports[t.local_node.node_id] = t

    # -- disruption control ----------------------------------------------

    def set_link(self, a: DiscoveryNode, b: DiscoveryNode,
                 state: str, bidirectional: bool = True) -> None:
        self._links[(a.node_id, b.node_id)] = state
        if bidirectional:
            self._links[(b.node_id, a.node_id)] = state

    def partition(self, group_a: List[DiscoveryNode],
                  group_b: List[DiscoveryNode],
                  mode: str = DISCONNECTED) -> None:
        for a in group_a:
            for b in group_b:
                self.set_link(a, b, mode)

    def isolate(self, node: DiscoveryNode, others: List[DiscoveryNode],
                mode: str = BLACKHOLE) -> None:
        self.partition([node],
                       [o for o in others if o.node_id != node.node_id],
                       mode)

    def heal(self) -> None:
        self._links.clear()

    def link_state(self, a: DiscoveryNode, b: DiscoveryNode) -> str:
        if a.node_id == b.node_id:
            return CONNECTED
        return self._links.get((a.node_id, b.node_id), CONNECTED)

    def _delay(self) -> float:
        return self.queue.random.uniform(self.min_delay, self.max_delay)

    # -- delivery ---------------------------------------------------------

    def deliver(self, sender: DisruptableTransport, dest: DiscoveryNode,
                action: str, request: Any, handler: ResponseHandler,
                timeout: Optional[float]) -> None:
        src = sender.local_node
        completed = {"done": False}

        def complete_ok(resp):
            if not completed["done"]:
                completed["done"] = True
                handler.on_response(resp)

        def complete_err(exc):
            if not completed["done"]:
                completed["done"] = True
                handler.on_failure(exc)

        if timeout is not None:
            self.queue.schedule(
                timeout,
                lambda: complete_err(
                    TimeoutError(f"[{dest.name}][{action}] timed out")),
                f"timeout {action}->{dest.name}")

        def request_leg():
            state = self.link_state(src, dest)
            target = self.transports.get(dest.node_id)
            if state == BLACKHOLE or target is None:
                return  # vanishes; only the timeout can complete it
            if state == DISCONNECTED:
                self.queue.schedule(
                    0, lambda: complete_err(
                        ConnectionError(f"[{dest.name}] disconnected")),
                    f"connect-fail {action}")
                return

            def respond(payload: Any, is_error: bool) -> None:
                def response_leg():
                    # response leg checks the reverse link at its own
                    # delivery time
                    if self.link_state(dest, src) != CONNECTED:
                        return
                    if is_error:
                        exc = SimRemoteException(str(payload))
                        # mirror BaseTransport._dispatch_response: the
                        # remote exception class travels with the error
                        # so failover can classify retryability
                        if isinstance(payload, dict):
                            exc.remote_type = payload.get(
                                "type", "exception")
                        complete_err(exc)
                    else:
                        complete_ok(payload)
                self.queue.schedule(self._delay(), response_leg,
                                    f"response {action} {dest.name}->{src.name}")

            target.handle(src, action, request, respond)

        self.queue.schedule(self._delay(), request_leg,
                            f"request {action} {src.name}->{dest.name}")


class SimRemoteException(Exception):
    remote_type = "exception"


# ------------------------------------------------- linearizability checker

@dataclass
class HistoryEvent:
    kind: str          # "invoke" | "response"
    process: int
    op_id: int
    value: Any = None


class History:
    """Record of concurrent invocations/responses (ref:
    LinearizabilityChecker.History)."""

    def __init__(self) -> None:
        self.events: List[HistoryEvent] = []
        self._next_op = itertools.count()

    def invoke(self, process: int, value: Any) -> int:
        op = next(self._next_op)
        self.events.append(HistoryEvent("invoke", process, op, value))
        return op

    def respond(self, process: int, op_id: int, value: Any) -> None:
        self.events.append(HistoryEvent("response", process, op_id, value))

    def complete_pending(self, infer: Callable[[Any], Any]) -> None:
        """Close any open invocations with an inferred response (the
        checker may also simply drop them if None is returned)."""
        responded = {e.op_id for e in self.events if e.kind == "response"}
        for e in list(self.events):
            if e.kind == "invoke" and e.op_id not in responded:
                self.respond(e.process, e.op_id, infer(e.value))


def check_linearizable(sequential_spec: "SequentialSpec",
                       history: History,
                       max_states: int = 2_000_000) -> bool:
    """Wing & Gong / Lowe-style search (ref:
    LinearizabilityChecker.java:53,230): try all valid permutations of
    concurrent ops against the sequential spec, memoising visited
    (linearized-set, state) pairs."""
    ops: Dict[int, Tuple[Any, Any]] = {}
    order: List[int] = []
    responded: Set[int] = set()
    for e in history.events:
        if e.kind == "invoke":
            ops[e.op_id] = (e.value, None)
            order.append(e.op_id)
        else:
            inp = ops[e.op_id][0]
            ops[e.op_id] = (inp, e.value)
            responded.add(e.op_id)
    # drop ops that never responded — a dropped op may or may not have
    # taken effect; to stay sound the caller should infer responses for
    # writes that might have been applied (complete_pending)
    order = [o for o in order if o in responded]

    # intervals: op -> (invoke_index, response_index)
    inv_i: Dict[int, int] = {}
    res_i: Dict[int, int] = {}
    for i, e in enumerate(history.events):
        if e.op_id not in responded:
            continue
        if e.kind == "invoke":
            inv_i[e.op_id] = i
        else:
            res_i[e.op_id] = i

    init = sequential_spec.initial_state()
    seen: Set[Tuple[FrozenSetLike, Any]] = set()
    states_explored = 0

    def minimal_response_index(pending: List[int]) -> int:
        return min(res_i[o] for o in pending) if pending else -1

    def search(linearized: frozenset, state: Any) -> bool:
        nonlocal states_explored
        states_explored += 1
        if states_explored > max_states:
            raise AssertionError("linearizability search exploded")
        remaining = [o for o in order if o not in linearized]
        if not remaining:
            return True
        key = (linearized, sequential_spec.fingerprint(state))
        if key in seen:
            return False
        seen.add(key)
        # an op is a candidate next linearization point iff its invocation
        # precedes the earliest response among remaining ops (no completed
        # op may be reordered after one that responded before it started)
        first_res = minimal_response_index(remaining)
        for op in remaining:
            if inv_i[op] > first_res:
                continue
            inp, outp = ops[op]
            legal, nxt = sequential_spec.apply(state, inp, outp)
            if not legal:
                continue
            if search(linearized | {op}, nxt):
                return True
        return False

    return search(frozenset(), init)


FrozenSetLike = frozenset


class SequentialSpec:
    """Sequential datatype spec for the checker."""

    def initial_state(self) -> Any:
        raise NotImplementedError

    def apply(self, state: Any, inp: Any, outp: Any) -> Tuple[bool, Any]:
        """Return (legal, next_state): whether (inp → outp) is a legal
        transition from `state`, and the state after it."""
        raise NotImplementedError

    def fingerprint(self, state: Any) -> Any:
        return state


class RegisterSpec(SequentialSpec):
    """A single read/write register (what the reference checks cluster
    state against). Ops: ("write", v) → "ok"; ("read", None) → v."""

    def initial_state(self):
        return None

    def apply(self, state, inp, outp):
        kind, val = inp
        if kind == "write":
            return (outp in ("ok", None, "maybe"), val)
        if kind == "read":
            return (outp == state, state)
        return (False, state)
