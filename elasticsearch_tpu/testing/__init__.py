"""Test framework (ships as a component, like the reference's
test/framework): deterministic simulation harness, disruptable transport,
linearizability checker (ref: SURVEY.md §4.3)."""

from elasticsearch_tpu.testing.deterministic import (  # noqa: F401
    BLACKHOLE,
    CONNECTED,
    DISCONNECTED,
    DeterministicTaskQueue,
    DisruptableTransport,
    History,
    RegisterSpec,
    Scheduler,
    SequentialSpec,
    SimNetwork,
    ThreadedScheduler,
    check_linearizable,
)
