"""Circuit breakers: hierarchical memory accounting.

Mirrors the reference's hierarchical circuit-breaker service (ref:
indices/breaker/HierarchyCircuitBreakerService.java, common/breaker/
ChildMemoryCircuitBreaker.java): child breakers (request, fielddata,
in_flight_requests) each with their own limit, plus a parent limit over the
sum. On TPU the accounted resource is host staging memory headed for HBM.
"""

from __future__ import annotations

import threading
from typing import Dict

from elasticsearch_tpu.common.errors import CircuitBreakingException


def _human_size(n: int) -> str:
    for unit in ("b", "kb", "mb", "gb", "tb"):
        if abs(n) < 1024 or unit == "tb":
            return f"{n:.1f}{unit}" if unit != "b" else f"{n}b"
        n /= 1024
    return f"{n}b"


class CircuitBreaker:
    PARENT = "parent"
    REQUEST = "request"
    FIELDDATA = "fielddata"
    IN_FLIGHT_REQUESTS = "in_flight_requests"
    # TPU-native child: device-resident segment/filter-mask bytes — fed
    # by DeviceSegmentCache admission (search/context.py), where passing
    # the limit applies LRU eviction pressure before tripping
    HBM = "hbm"

    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0,
                 parent: "HierarchyCircuitBreakerService" = None):
        self.name = name
        self.limit = limit_bytes
        self.overhead = overhead
        self._used = 0
        self._trip_count = 0
        self._lock = threading.Lock()
        self._parent = parent

    @property
    def used(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count

    def set_limit(self, limit_bytes: int) -> None:
        """Dynamic resize (the `indices.breaker.*.limit` settings are
        dynamic in the reference; the memory-pressure fault in
        testing/faults.py shrinks limits mid-flight through this)."""
        with self._lock:
            self.limit = int(limit_bytes)

    def _on_trip(self, label: str) -> None:
        self._trip_count += 1
        svc = self._parent
        if svc is not None and svc.metrics is not None:
            svc.metrics.inc("breaker.tripped", breaker=self.name)
        if svc is not None and getattr(svc, "tenants", None) is not None:
            from elasticsearch_tpu.telemetry import context as _telectx
            svc.tenants.record_breaker_trip(
                _telectx.current_tenant(), self.name)

    def add_estimate_bytes_and_maybe_break(self, bytes_: int, label: str = "") -> int:
        with self._lock:
            new_used = self._used + bytes_
            if self.limit >= 0 and new_used * self.overhead > self.limit:
                self._on_trip(label)
                raise CircuitBreakingException(
                    f"[{self.name}] Data too large, data for [{label}] would be "
                    f"[{_human_size(new_used)}/{new_used}b], which is larger than "
                    f"the limit of [{_human_size(self.limit)}/{self.limit}b]",
                    bytes_wanted=new_used, bytes_limit=self.limit)
            self._used = new_used
        if self._parent is not None:
            try:
                self._parent.check_parent_limit(label)
            except CircuitBreakingException:
                with self._lock:
                    self._used -= bytes_
                raise
        return self._used

    def add_without_breaking(self, bytes_: int) -> int:
        with self._lock:
            self._used += bytes_
            return self._used

    def release(self, bytes_: int):
        self.add_without_breaking(-bytes_)


class NoneCircuitBreaker(CircuitBreaker):
    """Never breaks (ref: common/breaker/NoopCircuitBreaker.java)."""

    def __init__(self, name: str = "noop"):
        super().__init__(name, limit_bytes=-1)


class HierarchyCircuitBreakerService:
    """Parent limit across child breakers (ref:
    indices/breaker/HierarchyCircuitBreakerService.java)."""

    def __init__(self, total_limit_bytes: int = 4 * 1024 ** 3,
                 request_limit_bytes: int = None,
                 fielddata_limit_bytes: int = None,
                 hbm_limit_bytes: int = None,
                 metrics=None):
        self.total_limit = total_limit_bytes
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._parent_trip_count = 0
        # telemetry sink (MetricsRegistry or None) — `breaker.tripped`
        # counters per child, `breaker.parent.tripped` for the parent
        self.metrics = metrics
        # optional TenantAccounting sink: trips charged to the ambient
        # tenant so noisy-neighbor attribution sees who blew the budget
        self.tenants = None
        if request_limit_bytes is None:
            request_limit_bytes = int(total_limit_bytes * 0.6)
        if fielddata_limit_bytes is None:
            fielddata_limit_bytes = int(total_limit_bytes * 0.4)
        if hbm_limit_bytes is None:
            hbm_limit_bytes = total_limit_bytes
        for name, limit in [
            (CircuitBreaker.REQUEST, request_limit_bytes),
            (CircuitBreaker.FIELDDATA, fielddata_limit_bytes),
            (CircuitBreaker.IN_FLIGHT_REQUESTS, total_limit_bytes),
            (CircuitBreaker.HBM, hbm_limit_bytes),
        ]:
            self._breakers[name] = CircuitBreaker(name, limit, parent=self)

    def get_breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def breaker_names(self):
        return list(self._breakers)

    def check_parent_limit(self, label: str):
        # HBM is device memory, not host memory: it has its own budget
        # and doesn't consume the parent (host) allowance
        total = sum(b.used for name, b in self._breakers.items()
                    if name != CircuitBreaker.HBM)
        if self.total_limit >= 0 and total > self.total_limit:
            self._parent_trip_count += 1
            if self.metrics is not None:
                self.metrics.inc("breaker.tripped", breaker="parent")
            raise CircuitBreakingException(
                f"[parent] Data too large, data for [{label}] would be [{total}b], "
                f"which is larger than the limit of [{self.total_limit}b]",
                bytes_wanted=total, bytes_limit=self.total_limit)

    def stats(self) -> dict:
        host_used = sum(b.used for name, b in self._breakers.items()
                        if name != CircuitBreaker.HBM)
        return {
            "parent": {"limit_size_in_bytes": self.total_limit,
                       "estimated_size_in_bytes": host_used,
                       "tripped": self._parent_trip_count},
            **{name: {"limit_size_in_bytes": b.limit,
                      "estimated_size_in_bytes": b.used,
                      "tripped": b.trip_count}
               for name, b in self._breakers.items()},
        }


def payload_size_bytes(payload) -> int:
    """Byte-size estimate of an arbitrary request/operation payload for
    breaker and indexing-pressure accounting — THE shared sizer (the
    transport inbound charge and IndexingPressure both use it, so the
    two accountings can never drift): raw byte/str payloads by length,
    structured payloads by json-encoded length (proportional to the
    host memory they occupy in flight), with a conservative fallback."""
    if payload is None:
        return 0
    if isinstance(payload, (bytes, bytearray, str)):
        return len(payload)
    import json
    try:
        return len(json.dumps(payload, default=str))
    except (TypeError, ValueError):
        import sys
        return sys.getsizeof(payload)


def build_breaker_service(settings_get,
                          metrics=None) -> HierarchyCircuitBreakerService:
    """Construct a node breaker service from settings — the ONE place
    `indices.breaker.*.limit` parsing and defaulting lives (Node and
    ClusterNode share it). An explicit 0 limit is honored (reject
    everything), not silently replaced by the default."""
    from elasticsearch_tpu.common.settings import parse_byte_size

    def limit(key, default):
        raw = settings_get(key)
        return parse_byte_size(raw, key) if raw is not None else default

    total = limit("indices.breaker.total.limit", 4 * 1024 ** 3)
    request = limit("indices.breaker.request.limit", None)
    return HierarchyCircuitBreakerService(
        total_limit_bytes=total,
        request_limit_bytes=(request if request is not None
                             else int(total * 0.6)),
        fielddata_limit_bytes=limit("indices.breaker.fielddata.limit",
                                    None),
        hbm_limit_bytes=limit("indices.breaker.hbm.limit", None),
        metrics=metrics)


class NoneCircuitBreakerService(HierarchyCircuitBreakerService):
    def __init__(self):
        super().__init__(total_limit_bytes=-1)
        self._breakers = {
            name: NoneCircuitBreaker(name)
            for name in (CircuitBreaker.REQUEST, CircuitBreaker.FIELDDATA,
                         CircuitBreaker.IN_FLIGHT_REQUESTS,
                         CircuitBreaker.HBM)
        }

    def check_parent_limit(self, label: str):
        pass
