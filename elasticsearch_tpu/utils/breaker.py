"""Circuit breakers: hierarchical memory accounting.

Mirrors the reference's hierarchical circuit-breaker service (ref:
indices/breaker/HierarchyCircuitBreakerService.java, common/breaker/
ChildMemoryCircuitBreaker.java): child breakers (request, fielddata,
in_flight_requests) each with their own limit, plus a parent limit over the
sum. On TPU the accounted resource is host staging memory headed for HBM.
"""

from __future__ import annotations

import threading
from typing import Dict

from elasticsearch_tpu.common.errors import CircuitBreakingException


def _human_size(n: int) -> str:
    for unit in ("b", "kb", "mb", "gb", "tb"):
        if abs(n) < 1024 or unit == "tb":
            return f"{n:.1f}{unit}" if unit != "b" else f"{n}b"
        n /= 1024
    return f"{n}b"


class CircuitBreaker:
    PARENT = "parent"
    REQUEST = "request"
    FIELDDATA = "fielddata"
    IN_FLIGHT_REQUESTS = "in_flight_requests"

    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0,
                 parent: "HierarchyCircuitBreakerService" = None):
        self.name = name
        self.limit = limit_bytes
        self.overhead = overhead
        self._used = 0
        self._trip_count = 0
        self._lock = threading.Lock()
        self._parent = parent

    @property
    def used(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count

    def add_estimate_bytes_and_maybe_break(self, bytes_: int, label: str = "") -> int:
        with self._lock:
            new_used = self._used + bytes_
            if self.limit >= 0 and new_used * self.overhead > self.limit:
                self._trip_count += 1
                raise CircuitBreakingException(
                    f"[{self.name}] Data too large, data for [{label}] would be "
                    f"[{_human_size(new_used)}/{new_used}b], which is larger than "
                    f"the limit of [{_human_size(self.limit)}/{self.limit}b]",
                    bytes_wanted=new_used, bytes_limit=self.limit)
            self._used = new_used
        if self._parent is not None:
            try:
                self._parent.check_parent_limit(label)
            except CircuitBreakingException:
                with self._lock:
                    self._used -= bytes_
                raise
        return self._used

    def add_without_breaking(self, bytes_: int) -> int:
        with self._lock:
            self._used += bytes_
            return self._used

    def release(self, bytes_: int):
        self.add_without_breaking(-bytes_)


class NoneCircuitBreaker(CircuitBreaker):
    """Never breaks (ref: common/breaker/NoopCircuitBreaker.java)."""

    def __init__(self, name: str = "noop"):
        super().__init__(name, limit_bytes=-1)


class HierarchyCircuitBreakerService:
    """Parent limit across child breakers (ref:
    indices/breaker/HierarchyCircuitBreakerService.java)."""

    def __init__(self, total_limit_bytes: int = 4 * 1024 ** 3,
                 request_limit_bytes: int = None,
                 fielddata_limit_bytes: int = None):
        self.total_limit = total_limit_bytes
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._parent_trip_count = 0
        if request_limit_bytes is None:
            request_limit_bytes = int(total_limit_bytes * 0.6)
        if fielddata_limit_bytes is None:
            fielddata_limit_bytes = int(total_limit_bytes * 0.4)
        for name, limit in [
            (CircuitBreaker.REQUEST, request_limit_bytes),
            (CircuitBreaker.FIELDDATA, fielddata_limit_bytes),
            (CircuitBreaker.IN_FLIGHT_REQUESTS, total_limit_bytes),
        ]:
            self._breakers[name] = CircuitBreaker(name, limit, parent=self)

    def get_breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def check_parent_limit(self, label: str):
        total = sum(b.used for b in self._breakers.values())
        if total > self.total_limit:
            self._parent_trip_count += 1
            raise CircuitBreakingException(
                f"[parent] Data too large, data for [{label}] would be [{total}b], "
                f"which is larger than the limit of [{self.total_limit}b]",
                bytes_wanted=total, bytes_limit=self.total_limit)

    def stats(self) -> dict:
        return {
            "parent": {"limit_size_in_bytes": self.total_limit,
                       "estimated_size_in_bytes": sum(b.used for b in self._breakers.values()),
                       "tripped": self._parent_trip_count},
            **{name: {"limit_size_in_bytes": b.limit,
                      "estimated_size_in_bytes": b.used,
                      "tripped": b.trip_count}
               for name, b in self._breakers.items()},
        }


class NoneCircuitBreakerService(HierarchyCircuitBreakerService):
    def __init__(self):
        super().__init__(total_limit_bytes=-1)
        self._breakers = {
            name: NoneCircuitBreaker(name)
            for name in (CircuitBreaker.REQUEST, CircuitBreaker.FIELDDATA,
                         CircuitBreaker.IN_FLIGHT_REQUESTS)
        }

    def check_parent_limit(self, label: str):
        pass
