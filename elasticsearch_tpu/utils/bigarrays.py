"""BigArrays: breaker-accounted array allocation.

Mirrors the reference's BigArrays/PageCacheRecycler (ref:
common/util/BigArrays.java:36,357-379): allocations are accounted against a
circuit breaker before being handed out, and released back on close. Here the
arrays are numpy host buffers that stage data for transfer into TPU HBM, so
the accounting guards host staging memory the way BigArrays guards the JVM
heap.
"""

from __future__ import annotations

import numpy as np

from elasticsearch_tpu.utils.breaker import (
    CircuitBreaker,
    HierarchyCircuitBreakerService,
    NoneCircuitBreakerService,
)


class AccountedArray:
    """A numpy array whose bytes are registered with a circuit breaker."""

    def __init__(self, array: np.ndarray, bigarrays: "BigArrays"):
        self.array = array
        self._bigarrays = bigarrays
        self._released = False

    @property
    def nbytes(self) -> int:
        return self.array.nbytes

    def close(self):
        if not self._released:
            self._bigarrays._release(self.array.nbytes)
            self._released = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BigArrays:
    def __init__(self, breaker_service: HierarchyCircuitBreakerService = None,
                 breaker_name: str = CircuitBreaker.REQUEST):
        self._service = breaker_service or NoneCircuitBreakerService()
        self._breaker = self._service.get_breaker(breaker_name)

    def new_array(self, shape, dtype, label: str = "array") -> AccountedArray:
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        self._breaker.add_estimate_bytes_and_maybe_break(nbytes, label)
        try:
            arr = np.zeros(shape, dtype=dtype)
        except MemoryError:
            self._breaker.release(nbytes)
            raise
        return AccountedArray(arr, self)

    def adopt(self, array: np.ndarray, label: str = "array") -> AccountedArray:
        """Account an existing array."""
        self._breaker.add_estimate_bytes_and_maybe_break(array.nbytes, label)
        return AccountedArray(array, self)

    def _release(self, nbytes: int):
        self._breaker.release(nbytes)

    @classmethod
    def non_breaking(cls) -> "BigArrays":
        return cls(NoneCircuitBreakerService())
