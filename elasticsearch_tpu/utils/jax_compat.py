"""JAX API compatibility shims.

ONE resolver for ``shard_map`` (the mesh layer's SPMD seam): modern jax
exports it as ``jax.shard_map`` (with a ``check_vma`` kwarg); the 0.4.x
line this environment ships only has
``jax.experimental.shard_map.shard_map`` (whose equivalent kwarg is
``check_rep``). Every shard_map call site in the repo
(parallel/sharded.py, parallel/mesh_executor.py, ops/plan.py) goes
through this shim so the mesh layer runs — and is TESTABLE on the CPU
virtual-device mesh — on both API generations instead of failing with
``AttributeError: module 'jax' has no attribute 'shard_map'``.

Usage matches the modern API::

    from elasticsearch_tpu.utils.jax_compat import shard_map

    @partial(shard_map, mesh=mesh, check_vma=False,
             in_specs=(P("shard"),), out_specs=P())
    def step(x): ...
"""

from __future__ import annotations

from typing import Callable, Optional

import jax


def _resolve():
    """(impl, replication-check kwarg name) for this jax version."""
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        return impl, "check_vma"
    from jax.experimental.shard_map import shard_map as impl
    return impl, "check_rep"


_SHARD_MAP, _CHECK_KW = _resolve()


def shard_map(f: Optional[Callable] = None, *, mesh=None, in_specs=None,
              out_specs=None, check_vma: Optional[bool] = None, **kw):
    """Version-portable ``shard_map`` with the MODERN signature.

    ``check_vma`` maps onto the old API's ``check_rep`` (both toggle
    the per-output replication/varying-axes check; the mesh kernels
    disable it because their all_gather/psum merges produce replicated
    outputs the checker cannot always prove). Supports both direct and
    ``partial``-decorator call styles, like the real thing.
    """
    if check_vma is not None:
        kw[_CHECK_KW] = bool(check_vma)
    if f is None:
        from functools import partial
        return partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs,
                       **({"check_vma": check_vma}
                          if check_vma is not None else {}))
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
