"""Ingest pipelines: per-document transforms before indexing.

Mirrors the reference's ingest layer (ref: ingest/IngestService.java:81,
449,508 — pipeline registry + executeBulkRequest detour; ingest/
Pipeline.java, CompoundProcessor.java — processor chain with on_failure;
modules/ingest-common — the ~30 built-in processor types, of which the
core set is implemented here). Pipelines run on the host CPU — this is
string/JSON work with no batch structure, exactly the part of the stack
that should NOT be on the TPU.

Supported processors: set, remove, rename, convert, lowercase, uppercase,
trim, split, join, append, gsub, date, json, fail, drop, script, pipeline,
dissect (lite), grok (lite — named COMMONAPACHELOG-style patterns are out
of scope; %{NAME:field} with regex classes works), foreach, dot_expander,
csv, kv, html_strip, urldecode, bytes, uppercase/lowercase, fingerprint.

Failure handling matches the reference: a processor failure aborts the
pipeline unless the processor (or pipeline) declares ``on_failure``
handlers, which then run with the error recorded in ingest metadata
(ref: CompoundProcessor.executeOnFailure).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from html.parser import HTMLParser
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import unquote

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    ResourceNotFoundException,
)


class IngestProcessorException(ElasticsearchTpuException):
    status = 500


class DropException(Exception):
    """Raised by the drop processor — the document is silently discarded."""


class _PipelineCycleError(IngestProcessorException):
    pass


# ---------------------------------------------------------------------------
# Document model
# ---------------------------------------------------------------------------

class IngestDocument:
    """Mutable document under transformation (ref: ingest/IngestDocument
    — dot-path field access over source + metadata + ingest metadata)."""

    def __init__(self, source: Dict[str, Any], index: Optional[str] = None,
                 doc_id: Optional[str] = None,
                 routing: Optional[str] = None):
        self.source = source
        self.meta: Dict[str, Any] = {"_index": index, "_id": doc_id}
        if routing is not None:
            self.meta["_routing"] = routing
        self.ingest_meta: Dict[str, Any] = {
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime()),
        }

    # -- dot-path access ----------------------------------------------------
    def _resolve(self, path: str, create: bool = False
                 ) -> Tuple[Dict[str, Any], str]:
        if path.startswith("_ingest."):
            return self.ingest_meta, path[len("_ingest."):]
        if path in self.meta or path in ("_index", "_id", "_routing"):
            return self.meta, path
        node = self.source
        parts = path.split(".")
        for p in parts[:-1]:
            if not isinstance(node, dict):
                raise IngestProcessorException(
                    f"cannot resolve [{path}]: [{p}] is not an object")
            if p not in node:
                if not create:
                    raise IngestProcessorException(
                        f"field [{path}] not present as part of path [{p}]")
                node[p] = {}
            node = node[p]
        return node, parts[-1]

    def has(self, path: str) -> bool:
        try:
            node, leaf = self._resolve(path)
        except IngestProcessorException:
            return False
        return isinstance(node, dict) and leaf in node

    def get(self, path: str, default=None):
        try:
            node, leaf = self._resolve(path)
        except IngestProcessorException:
            return default
        if isinstance(node, dict) and leaf in node:
            return node[leaf]
        return default

    def set(self, path: str, value: Any) -> None:
        node, leaf = self._resolve(path, create=True)
        node[leaf] = value

    def remove(self, path: str) -> None:
        node, leaf = self._resolve(path)
        if not isinstance(node, dict) or leaf not in node:
            raise IngestProcessorException(f"field [{path}] not present")
        del node[leaf]

    def render(self, template: str) -> str:
        """Mustache-lite ``{{field}}`` / ``{{{field}}}`` substitution
        (ref: lang-mustache used by set/fail templates)."""
        def sub(m):
            v = self.get(m.group(1).strip())
            return "" if v is None else str(v)
        out = re.sub(r"\{\{\{(.+?)\}\}\}", sub, template)
        return re.sub(r"\{\{(.+?)\}\}", sub, out)


# ---------------------------------------------------------------------------
# Processors
# ---------------------------------------------------------------------------

Processor = Callable[[IngestDocument], None]
_PROCESSOR_FACTORIES: Dict[str, Callable[[Dict[str, Any], "IngestService"],
                                         Processor]] = {}


def processor(name: str):
    def deco(factory):
        _PROCESSOR_FACTORIES[name] = factory
        return factory
    return deco


def _if_wraps(cfg: Dict[str, Any], fn: Processor) -> Processor:
    """Conditional execution (ref: ConditionalProcessor — painless `if`;
    here the same sandboxed expression engine, evaluated per doc)."""
    cond = cfg.get("if")
    if cond is None:
        return fn
    compiled = _compile_condition(cond)

    def wrapped(doc: IngestDocument):
        if compiled(doc):
            fn(doc)
    return wrapped


@processor("set")
def _set(cfg, svc):
    field = cfg["field"]
    override = cfg.get("override", True)
    value = cfg.get("value")
    copy_from = cfg.get("copy_from")

    def fn(doc):
        if not override and doc.get(field) is not None:
            return
        if copy_from is not None:
            doc.set(field, doc.get(copy_from))
        elif isinstance(value, str):
            doc.set(field, doc.render(value))
        else:
            doc.set(field, value)
    return fn


@processor("remove")
def _remove(cfg, svc):
    fields = cfg["field"]
    if isinstance(fields, str):
        fields = [fields]
    ignore_missing = cfg.get("ignore_missing", False)

    def fn(doc):
        for f in fields:
            try:
                doc.remove(f)
            except IngestProcessorException:
                if not ignore_missing:
                    raise
    return fn


@processor("rename")
def _rename(cfg, svc):
    field, target = cfg["field"], cfg["target_field"]
    ignore_missing = cfg.get("ignore_missing", False)

    def fn(doc):
        if not doc.has(field):
            if ignore_missing:
                return
            raise IngestProcessorException(f"field [{field}] not present")
        doc.set(target, doc.get(field))
        doc.remove(field)
    return fn


@processor("convert")
def _convert(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    type_ = cfg["type"]
    ignore_missing = cfg.get("ignore_missing", False)
    casts = {
        "integer": int, "long": int, "float": float, "double": float,
        "string": str,
        "boolean": lambda v: (v if isinstance(v, bool)
                              else str(v).lower() == "true"),
        "auto": lambda v: _auto_cast(v),
    }
    if type_ not in casts:
        raise IllegalArgumentException(f"type [{type_}] not supported")
    cast = casts[type_]

    def fn(doc):
        v = doc.get(field)
        if v is None:
            if ignore_missing:
                return
            raise IngestProcessorException(f"field [{field}] not present")
        try:
            doc.set(target, [cast(x) for x in v] if isinstance(v, list)
                    else cast(v))
        except (ValueError, TypeError) as e:
            raise IngestProcessorException(
                f"unable to convert [{v}] to {type_}: {e}")
    return fn


def _auto_cast(v):
    if isinstance(v, (int, float, bool)):
        return v
    s = str(v)
    for cast in (int, float):
        try:
            return cast(s)
        except ValueError:
            pass
    if s.lower() in ("true", "false"):
        return s.lower() == "true"
    return s


def _string_transform(name: str, transform: Callable[[str], str]):
    @processor(name)
    def _factory(cfg, svc, _t=transform):
        field = cfg["field"]
        target = cfg.get("target_field", field)
        ignore_missing = cfg.get("ignore_missing", False)

        def fn(doc):
            v = doc.get(field)
            if v is None:
                if ignore_missing:
                    return
                raise IngestProcessorException(f"field [{field}] not present")
            doc.set(target, [_t(x) for x in v] if isinstance(v, list)
                    else _t(v))
        return fn
    return _factory


_string_transform("lowercase", str.lower)
_string_transform("uppercase", str.upper)
_string_transform("trim", str.strip)
_string_transform("urldecode", unquote)


@processor("split")
def _split(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    sep = re.compile(cfg["separator"])
    preserve = cfg.get("preserve_trailing", False)

    def fn(doc):
        v = doc.get(field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        parts = sep.split(str(v))
        if not preserve:
            while parts and parts[-1] == "":
                parts.pop()
        doc.set(target, parts)
    return fn


@processor("join")
def _join(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    sep = cfg["separator"]

    def fn(doc):
        v = doc.get(field)
        if not isinstance(v, list):
            raise IngestProcessorException(
                f"field [{field}] of type [{type(v).__name__}] cannot be "
                "joined")
        doc.set(target, sep.join(str(x) for x in v))
    return fn


@processor("append")
def _append(cfg, svc):
    field = cfg["field"]
    value = cfg["value"]
    allow_dups = cfg.get("allow_duplicates", True)

    def fn(doc):
        cur = doc.get(field)
        if cur is None:
            cur = []
        elif not isinstance(cur, list):
            cur = [cur]
        else:
            cur = list(cur)
        add = value if isinstance(value, list) else [value]
        add = [doc.render(v) if isinstance(v, str) else v for v in add]
        for v in add:
            if allow_dups or v not in cur:
                cur.append(v)
        doc.set(field, cur)
    return fn


@processor("gsub")
def _gsub(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    pat = re.compile(cfg["pattern"])
    replacement = cfg["replacement"]

    def fn(doc):
        v = doc.get(field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        doc.set(target, pat.sub(replacement, str(v)))
    return fn


_DATE_FORMATS = {
    "ISO8601": None,  # handled by fromisoformat-ish parsing
    "UNIX": "unix", "UNIX_MS": "unix_ms",
}


@processor("date")
def _date(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", "@timestamp")
    formats = cfg.get("formats", ["ISO8601"])

    def fn(doc):
        from datetime import datetime, timezone
        v = doc.get(field)
        if v is None:
            raise IngestProcessorException(f"field [{field}] not present")
        for fmt in formats:
            try:
                if fmt == "ISO8601":
                    s = str(v).replace("Z", "+00:00")
                    dt = datetime.fromisoformat(s)
                elif fmt == "UNIX":
                    dt = datetime.fromtimestamp(float(v), tz=timezone.utc)
                elif fmt == "UNIX_MS":
                    dt = datetime.fromtimestamp(float(v) / 1000.0,
                                                tz=timezone.utc)
                else:
                    dt = datetime.strptime(str(v), fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=timezone.utc)
                doc.set(target, dt.isoformat().replace("+00:00", "Z"))
                return
            except (ValueError, OverflowError):
                continue
        raise IngestProcessorException(
            f"unable to parse date [{v}] with formats {formats}")
    return fn


@processor("json")
def _json(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    add_to_root = cfg.get("add_to_root", False)

    def fn(doc):
        v = doc.get(field)
        try:
            parsed = json.loads(v)
        except (TypeError, json.JSONDecodeError) as e:
            raise IngestProcessorException(f"unable to parse JSON: {e}")
        if add_to_root:
            if not isinstance(parsed, dict):
                raise IngestProcessorException(
                    "cannot add non-object to document root")
            doc.source.update(parsed)
        else:
            doc.set(target, parsed)
    return fn


@processor("fail")
def _fail(cfg, svc):
    message = cfg["message"]

    def fn(doc):
        raise IngestProcessorException(doc.render(message))
    return fn


@processor("drop")
def _drop(cfg, svc):
    def fn(doc):
        raise DropException()
    return fn


@processor("script")
def _script(cfg, svc):
    script = cfg.get("script", cfg)
    source = script.get("source") if isinstance(script, dict) else str(script)
    params = script.get("params", {}) if isinstance(script, dict) else {}
    compiled = _compile_ingest_script(source)

    def fn(doc):
        compiled(doc, params)
    return fn


@processor("pipeline")
def _pipeline(cfg, svc):
    name = cfg["name"]

    def fn(doc):
        svc.run_pipeline(name, doc)
    return fn


@processor("foreach")
def _foreach(cfg, svc):
    field = cfg["field"]
    inner_cfg = cfg["processor"]
    (ptype, pcfg), = inner_cfg.items()
    inner = _PROCESSOR_FACTORIES[ptype](pcfg, svc)

    def fn(doc):
        values = doc.get(field)
        if not isinstance(values, list):
            raise IngestProcessorException(
                f"field [{field}] is not a list")
        out = []
        for v in values:
            # the element is addressable BOTH ways: the reference's
            # `_ingest._value` convention (ingest metadata namespace)
            # and the bare `_value`
            sub = IngestDocument({"_value": v})
            sub.meta = doc.meta
            sub.ingest_meta["_value"] = v
            inner(sub)
            iv = sub.ingest_meta.get("_value")
            pv = sub.source.get("_value")
            out.append(iv if iv != v else pv)
        doc.set(field, out)
    return fn


@processor("dot_expander")
def _dot_expander(cfg, svc):
    field = cfg["field"]

    def fn(doc):
        if field == "*":
            keys = [k for k in list(doc.source) if "." in k]
        else:
            keys = [field] if field in doc.source else []
        for k in keys:
            v = doc.source.pop(k)
            doc.set(k, v)
    return fn


@processor("csv")
def _csv(cfg, svc):
    field = cfg["field"]
    targets = cfg["target_fields"]
    sep = cfg.get("separator", ",")
    quote = cfg.get("quote", '"')

    def fn(doc):
        import csv as _csv
        import io
        v = doc.get(field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        row = next(_csv.reader(io.StringIO(str(v)), delimiter=sep,
                               quotechar=quote))
        for t, val in zip(targets, row):
            doc.set(t, val)
    return fn


@processor("kv")
def _kv(cfg, svc):
    field = cfg["field"]
    field_split = cfg["field_split"]
    value_split = cfg["value_split"]
    target = cfg.get("target_field")
    prefix = cfg.get("prefix", "")

    def fn(doc):
        v = doc.get(field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        for pair in re.split(field_split, str(v)):
            if not pair:
                continue
            parts = re.split(value_split, pair, maxsplit=1)
            if len(parts) != 2:
                continue
            key = prefix + parts[0]
            doc.set(f"{target}.{key}" if target else key, parts[1])
    return fn


class _HTMLStripper(HTMLParser):
    def __init__(self):
        super().__init__()
        self.chunks: List[str] = []

    def handle_data(self, data):
        self.chunks.append(data)


@processor("html_strip")
def _html_strip(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)

    def fn(doc):
        v = doc.get(field)
        if v is None:
            if cfg.get("ignore_missing"):
                return
            raise IngestProcessorException(f"field [{field}] not present")
        stripper = _HTMLStripper()
        stripper.feed(str(v))
        doc.set(target, "".join(stripper.chunks))
    return fn


@processor("bytes")
def _bytes(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    units = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3,
             "tb": 1024**4, "pb": 1024**5}

    def fn(doc):
        v = str(doc.get(field)).strip().lower()
        m = re.fullmatch(r"([\d.]+)\s*([kmgtp]?b)", v)
        if not m:
            raise IngestProcessorException(
                f"failed to parse setting [{field}] with value [{v}]")
        doc.set(target, int(float(m.group(1)) * units[m.group(2)]))
    return fn


@processor("fingerprint")
def _fingerprint(cfg, svc):
    fields = sorted(cfg["fields"])
    target = cfg.get("target_field", "fingerprint")
    method = cfg.get("method", "SHA-1").lower().replace("-", "")

    def fn(doc):
        h = hashlib.new(method)
        for f in fields:
            v = doc.get(f)
            if v is not None:
                h.update(f.encode())
                h.update(json.dumps(v, sort_keys=True, default=str).encode())
        doc.set(target, h.hexdigest())
    return fn


@processor("dissect")
def _dissect(cfg, svc):
    """Lite dissect: %{key} segments split on the literal text between
    them (ref: ingest-common DissectProcessor)."""
    field = cfg["field"]
    pattern = cfg["pattern"]
    parts = re.split(r"%\{(.*?)\}", pattern)
    # parts = [lit0, key1, lit1, key2, lit2, ...]

    def fn(doc):
        v = str(doc.get(field, ""))
        pos = 0
        if parts[0]:
            if not v.startswith(parts[0]):
                raise IngestProcessorException(
                    f"dissect pattern did not match [{v}]")
            pos = len(parts[0])
        for i in range(1, len(parts), 2):
            key = parts[i]
            lit = parts[i + 1] if i + 1 < len(parts) else ""
            if lit:
                end = v.find(lit, pos)
                if end < 0:
                    raise IngestProcessorException(
                        f"dissect pattern did not match [{v}]")
            else:
                end = len(v)
            if key and not key.startswith("?"):
                doc.set(key, v[pos:end])
            pos = end + len(lit)
    return fn


@processor("grok")
def _grok(cfg, svc):
    """Lite grok: %{PATTERN:field} with a small built-in pattern set
    (ref: ingest-common GrokProcessor; full Oniguruma pattern library out
    of scope)."""
    field = cfg["field"]
    patterns = cfg["patterns"]
    builtins = {
        "WORD": r"\w+", "NUMBER": r"[-+]?\d+(?:\.\d+)?", "INT": r"[-+]?\d+",
        "IP": r"\d{1,3}(?:\.\d{1,3}){3}", "DATA": r".*?", "GREEDYDATA": r".*",
        "NOTSPACE": r"\S+", "SPACE": r"\s+", "UUID": r"[0-9a-fA-F-]{36}",
        "LOGLEVEL": r"(?:TRACE|DEBUG|INFO|WARN|ERROR|FATAL)",
    }
    compiled = []
    for p in patterns:
        def repl(m):
            pat, _, name = m.group(1).partition(":")
            base = builtins.get(pat, r".*?")
            return f"(?P<{name}>{base})" if name else f"(?:{base})"
        compiled.append(re.compile(re.sub(r"%\{(.*?)\}", repl, p)))

    def fn(doc):
        v = str(doc.get(field, ""))
        for rx in compiled:
            m = rx.search(v)
            if m:
                for k, val in m.groupdict().items():
                    if val is not None:
                        doc.set(k, val)
                return
        raise IngestProcessorException(
            f"Provided Grok expressions do not match field value: [{v}]")
    return fn


# ---------------------------------------------------------------------------
# Ingest scripts / conditions (sandboxed per-doc expression evaluation —
# the scalar sibling of the columnar search script engine)
# ---------------------------------------------------------------------------

import ast as _ast

_ING_ALLOWED = (
    _ast.Expression, _ast.Module, _ast.Expr, _ast.Assign, _ast.BinOp,
    _ast.UnaryOp, _ast.BoolOp, _ast.Compare, _ast.Call, _ast.Attribute,
    _ast.Subscript, _ast.Name, _ast.Constant, _ast.Load, _ast.Store,
    _ast.Add, _ast.Sub, _ast.Mult, _ast.Div, _ast.Mod, _ast.Pow,
    _ast.FloorDiv, _ast.USub, _ast.UAdd, _ast.Not, _ast.And, _ast.Or,
    _ast.Eq, _ast.NotEq, _ast.Lt, _ast.LtE, _ast.Gt, _ast.GtE,
    _ast.IfExp, _ast.List, _ast.Dict, _ast.Tuple, _ast.In, _ast.NotIn,
    _ast.Is, _ast.IsNot,
)

_SCRIPT_CACHE: Dict[str, Any] = {}
_SCRIPT_LOCK = threading.Lock()


class _AttrDict(dict):
    """params.name attribute access in ingest scripts."""

    def __getattr__(self, name):
        return self.get(name)


class _CtxView:
    """`ctx` object for ingest scripts: attribute/key access to source."""

    def __init__(self, doc: IngestDocument):
        object.__setattr__(self, "_doc", doc)

    def __getattr__(self, name):
        if name.startswith("_") and name in self._doc.meta:
            return self._doc.meta[name]
        return self._doc.source.get(name)

    def __setattr__(self, name, value):
        self._doc.source[name] = value

    def __getitem__(self, name):
        return self.__getattr__(name)

    def __setitem__(self, name, value):
        self._doc.source[name] = value

    def __contains__(self, name):
        return name in self._doc.source or name in self._doc.meta


_META_ATTRS = {"_index", "_id", "_routing", "_version", "_ingest", "_value"}


def _validate_ingest(tree, source: str):
    for node in _ast.walk(tree):
        if not isinstance(node, _ING_ALLOWED):
            raise IllegalArgumentException(
                f"ingest script: disallowed construct "
                f"[{type(node).__name__}] in [{source}]")
        if isinstance(node, _ast.Name) and node.id not in (
                "ctx", "params", "len", "str", "int", "float", "bool",
                "True", "False", "None"):
            raise IllegalArgumentException(
                f"ingest script: unknown name [{node.id}] in [{source}]")
        # sandbox: underscore attributes (except document metadata) are the
        # escape surface — ''.__class__.__mro__... (same rule as the search
        # script engine, search/script.py)
        if (isinstance(node, _ast.Attribute)
                and node.attr.startswith("_")
                and node.attr not in _META_ATTRS):
            raise IllegalArgumentException(
                f"ingest script: access to [{node.attr}] is not allowed "
                f"in [{source}]")


def _compile_ingest_script(source: str):
    with _SCRIPT_LOCK:
        cached = _SCRIPT_CACHE.get(("script", source))
    if cached is not None:
        return cached
    # the REAL language first (script/ — statements, loops, functions,
    # per-type method allowlists); the legacy python-expression
    # translation only remains for scripts Painless can't parse
    from elasticsearch_tpu.script import contexts as _plctx
    if _plctx.try_compile(source):
        def run(doc: IngestDocument, params: Dict[str, Any]):
            _plctx.run_ingest_script(source, doc, params)
    else:
        py = _painless_to_py(source, statements=True)
        tree = _ast.parse(py, mode="exec")
        _validate_ingest(tree, source)
        code = compile(tree, "<ingest_script>", "exec")

        def run(doc: IngestDocument, params: Dict[str, Any]):
            env = {"ctx": _CtxView(doc), "params": _AttrDict(params),
                   "len": len, "str": str, "int": int, "float": float,
                   "bool": bool}
            exec(code, {"__builtins__": {}}, env)

    with _SCRIPT_LOCK:
        _SCRIPT_CACHE[("script", source)] = run
    return run


def _painless_to_py(source: str, statements: bool = False) -> str:
    """Translate painless-style operators (&&, ||, !, null; `;` statement
    separators when ``statements``) to Python, leaving string literals
    untouched."""
    parts = re.split(r"('(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\")", source)
    out = []
    for i, part in enumerate(parts):
        if i % 2 == 1:  # a quoted literal
            out.append(part)
            continue
        p = part.replace("!=", "\x00ne\x00").replace("==", "\x00eq\x00")
        p = p.replace("&&", " and ").replace("||", " or ")
        p = p.replace("!", " not ")
        p = p.replace("\x00ne\x00", "!=").replace("\x00eq\x00", "==")
        p = re.sub(r"\bnull\b", "None", p)
        if statements:
            p = p.replace(";", "\n")
        out.append(p)
    return "".join(out)


def _compile_condition(source: str):
    with _SCRIPT_LOCK:
        cached = _SCRIPT_CACHE.get(("cond", source))
    if cached is not None:
        return cached
    from elasticsearch_tpu.script import contexts as _plctx
    if _plctx.try_compile(source):
        def run_pl(doc: IngestDocument) -> bool:
            return _plctx.run_ingest_condition(source, doc)
        with _SCRIPT_LOCK:
            _SCRIPT_CACHE[("cond", source)] = run_pl
        return run_pl
    py = _painless_to_py(source)
    tree = _ast.parse(py, mode="eval")
    _validate_ingest(tree, source)
    code = compile(tree, "<ingest_condition>", "eval")

    def run(doc: IngestDocument) -> bool:
        env = {"ctx": _CtxView(doc), "len": len, "str": str, "int": int,
               "float": float, "bool": bool}
        try:
            return bool(eval(code, {"__builtins__": {}}, env))
        except (TypeError, AttributeError):
            return False

    with _SCRIPT_LOCK:
        _SCRIPT_CACHE[("cond", source)] = run
    return run


# ---------------------------------------------------------------------------
# Pipeline + service
# ---------------------------------------------------------------------------

class Pipeline:
    """ref: ingest/Pipeline.java — an ordered CompoundProcessor with
    optional pipeline-level on_failure."""

    def __init__(self, pipeline_id: str, config: Dict[str, Any],
                 service: "IngestService"):
        self.id = pipeline_id
        self.description = config.get("description", "")
        self.version = config.get("version")
        self.config = config
        self._processors = [self._build(p, service)
                            for p in config.get("processors", [])]
        self._on_failure = [self._build(p, service)
                            for p in config.get("on_failure", [])]

    @staticmethod
    def _build(spec: Dict[str, Any], service: "IngestService"
               ) -> Tuple[str, Processor, List[Tuple[str, Processor]], bool]:
        if not isinstance(spec, dict) or len(spec) != 1:
            raise IllegalArgumentException(
                f"processor spec must have exactly one type key, got {spec}")
        (ptype, cfg), = spec.items()
        factory = _PROCESSOR_FACTORIES.get(ptype)
        if factory is None:
            raise IllegalArgumentException(
                f"No processor type exists with name [{ptype}]")
        try:
            fn = _if_wraps(cfg, factory(cfg, service))
        except ElasticsearchTpuException:
            raise
        except KeyError as e:
            raise IllegalArgumentException(
                f"[{ptype}] required property {e} is missing")
        except (re.error, SyntaxError, ValueError, TypeError) as e:
            raise IllegalArgumentException(
                f"[{ptype}] invalid configuration: {e}")
        on_failure = [Pipeline._build(p, service)
                      for p in cfg.get("on_failure", [])]
        return (ptype, fn, on_failure, cfg.get("ignore_failure", False))

    def execute(self, doc: IngestDocument) -> Optional[IngestDocument]:
        """Returns the transformed doc, or None if dropped."""
        try:
            self._run_chain(self._processors, doc)
        except DropException:
            return None
        except IngestProcessorException:
            if not self._on_failure:
                raise
            self._run_chain(self._on_failure, doc)
        return doc

    def execute_verbose(self, doc: IngestDocument) -> List[Dict[str, Any]]:
        """Per-processor trace for _simulate?verbose=true (ref:
        SimulateExecutionService — one result entry per processor)."""
        trace: List[Dict[str, Any]] = []
        for ptype, fn, on_failure, ignore_failure in self._processors:
            entry: Dict[str, Any] = {"processor_type": ptype}
            try:
                fn(doc)
                entry["status"] = "success"
                entry["doc"] = {
                    "_index": doc.meta.get("_index"),
                    "_id": doc.meta.get("_id"),
                    "_source": json.loads(json.dumps(doc.source)),
                    "_ingest": dict(doc.ingest_meta),
                }
            except DropException:
                entry["status"] = "dropped"
                trace.append(entry)
                break
            except ElasticsearchTpuException as e:
                if ignore_failure:
                    entry["status"] = "error_ignored"
                    entry["ignored_error"] = {"error": e.to_xcontent()}
                elif on_failure:
                    doc.ingest_meta["on_failure_message"] = str(e)
                    doc.ingest_meta["on_failure_processor_type"] = ptype
                    self._run_chain(on_failure, doc)
                    entry["status"] = "error"
                    entry["error"] = e.to_xcontent()
                else:
                    entry["status"] = "error"
                    entry["error"] = e.to_xcontent()
                    trace.append(entry)
                    break
            trace.append(entry)
        return trace

    def _run_chain(self, processors, doc: IngestDocument):
        for ptype, fn, on_failure, ignore_failure in processors:
            try:
                fn(doc)
            except DropException:
                raise
            except IngestProcessorException as e:
                if ignore_failure:
                    continue
                if on_failure:
                    doc.ingest_meta["on_failure_message"] = str(e)
                    doc.ingest_meta["on_failure_processor_type"] = ptype
                    self._run_chain(on_failure, doc)
                    continue
                raise
            except ElasticsearchTpuException:
                raise
            except Exception as e:  # processor bug → processor exception
                if ignore_failure:
                    continue
                raise IngestProcessorException(f"[{ptype}] {e}")


class IngestService:
    """Pipeline registry + execution (ref: IngestService.java:81 — stored
    in cluster state there; persisted to the node data path here, same
    durability from the single-node API's perspective)."""

    def __init__(self, data_path: Optional[str] = None):
        self._pipelines: Dict[str, Pipeline] = {}
        self._lock = threading.Lock()
        self._path = (os.path.join(data_path, "_ingest_pipelines.json")
                      if data_path else None)
        self._depth = threading.local()
        if self._path and os.path.exists(self._path):
            with open(self._path) as fh:
                for pid, cfg in json.load(fh).items():
                    self._pipelines[pid] = Pipeline(pid, cfg, self)

    def put_pipeline(self, pipeline_id: str, config: Dict[str, Any]):
        pipeline = Pipeline(pipeline_id, config, self)  # validates
        with self._lock:
            self._pipelines[pipeline_id] = pipeline
            self._persist()

    def get_pipeline(self, pipeline_id: str) -> Optional[Pipeline]:
        return self._pipelines.get(pipeline_id)

    def get_pipelines(self) -> Dict[str, Dict[str, Any]]:
        return {pid: p.config for pid, p in self._pipelines.items()}

    def delete_pipeline(self, pipeline_id: str):
        with self._lock:
            if pipeline_id not in self._pipelines:
                raise ResourceNotFoundException(
                    f"pipeline [{pipeline_id}] is missing")
            del self._pipelines[pipeline_id]
            self._persist()

    def _persist(self):
        if self._path:
            tmp = self._path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({pid: p.config
                           for pid, p in self._pipelines.items()}, fh)
            os.replace(tmp, self._path)

    # -- execution ----------------------------------------------------------
    def run_pipeline(self, pipeline_id: str,
                     doc: IngestDocument) -> Optional[IngestDocument]:
        pipeline = self._pipelines.get(pipeline_id)
        if pipeline is None:
            raise ResourceNotFoundException(
                f"pipeline with id [{pipeline_id}] does not exist")
        depth = getattr(self._depth, "value", 0)
        if depth >= 10:
            raise _PipelineCycleError(
                f"Max pipeline nesting depth exceeded at [{pipeline_id}]")
        self._depth.value = depth + 1
        try:
            return pipeline.execute(doc)
        finally:
            self._depth.value = depth

    def process(self, pipeline_id: str, index: str, doc_id: Optional[str],
                source: Dict[str, Any],
                routing: Optional[str] = None) -> Optional[IngestDocument]:
        """The bulk-path detour (ref: TransportBulkAction.java:172 →
        IngestService.executeBulkRequest): returns the transformed
        IngestDocument — pipelines may rewrite ``_index``/``_routing``
        metadata, which reroutes the doc — or None if dropped."""
        doc = IngestDocument(source, index=index, doc_id=doc_id,
                             routing=routing)
        return self.run_pipeline(pipeline_id, doc)

    def simulate(self, config_or_id, docs: List[Dict[str, Any]],
                 verbose: bool = False) -> Dict[str, Any]:
        """_ingest/pipeline/_simulate (ref: SimulatePipelineRequest)."""
        if isinstance(config_or_id, str):
            pipeline = self._pipelines.get(config_or_id)
            if pipeline is None:
                raise ResourceNotFoundException(
                    f"pipeline with id [{config_or_id}] does not exist")
        else:
            pipeline = Pipeline("_simulate_pipeline", config_or_id, self)
        results = []
        for entry in docs:
            source = entry.get("_source", {})
            doc = IngestDocument(
                json.loads(json.dumps(source)),  # deep copy
                index=entry.get("_index", "_index"),
                doc_id=entry.get("_id", "_id"))
            if verbose:
                results.append(
                    {"processor_results": pipeline.execute_verbose(doc)})
                continue
            try:
                out = pipeline.execute(doc)
                if out is None:
                    results.append({"doc": None})
                else:
                    results.append({"doc": {
                        "_index": out.meta.get("_index"),
                        "_id": out.meta.get("_id"),
                        "_source": out.source,
                        "_ingest": out.ingest_meta,
                    }})
            except ElasticsearchTpuException as e:
                results.append({"error": e.to_xcontent()})
        return {"docs": results}


# geoip/user_agent/attachment processors register on import (they live
# in their own modules the way ingest-geoip/-user-agent/-attachment are
# separate modules/plugins in the reference)
from elasticsearch_tpu.ingest import attachment  # noqa: E402,F401
from elasticsearch_tpu.ingest import geo_ua  # noqa: E402,F401
