"""GeoIP + user-agent ingest processors.

Mirrors the reference's ingest-geoip and ingest-user-agent modules (ref:
modules/ingest-geoip — MaxMind GeoLite2 lookups; modules/ingest-user-agent
— UA-parser regexes; SURVEY.md §2.4). Re-design for this zero-egress
engine: `geoip` resolves against a user-supplied JSON database file
(list of {network, ...geo fields} entries, the GeoLite2-equivalent the
operator provides) plus built-in entries for reserved/documentation
ranges so the processor is exercisable without any external database;
`user_agent` is a regex classifier covering the mainstream browser/bot
families (the ua-parser core patterns re-expressed)."""

from __future__ import annotations

import ipaddress
import json
import re
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.ingest.service import (
    IngestProcessorException,
    processor,
)

# documentation/reserved ranges (RFC 5737/3849) — usable without any
# database file, handy for tests and pipeline dry-runs
_BUILTIN_DB: List[Dict[str, Any]] = [
    {"network": "192.0.2.0/24", "country_iso_code": "ZZ",
     "country_name": "TEST-NET-1", "city_name": "Example City",
     "location": {"lat": 0.0, "lon": 0.0}},
    {"network": "198.51.100.0/24", "country_iso_code": "ZZ",
     "country_name": "TEST-NET-2"},
    {"network": "203.0.113.0/24", "country_iso_code": "ZZ",
     "country_name": "TEST-NET-3"},
]


class _GeoDb:
    def __init__(self, entries: List[Dict[str, Any]]):
        self.nets = []
        for e in entries:
            try:
                net = ipaddress.ip_network(e["network"])
            except (KeyError, ValueError):
                continue
            self.nets.append((net, {k: v for k, v in e.items()
                                    if k != "network"}))
        # longest prefix first so specific entries win
        self.nets.sort(key=lambda nv: -nv[0].prefixlen)

    def lookup(self, ip: str) -> Optional[Dict[str, Any]]:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return None
        for net, data in self.nets:
            if addr in net:
                return data
        if addr.is_private:
            return {"country_iso_code": "ZZ", "country_name": "Private"}
        return None


@processor("geoip")
def _geoip(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", "geoip")
    ignore_missing = bool(cfg.get("ignore_missing", False))
    properties = cfg.get("properties")
    entries = list(_BUILTIN_DB)
    db_file = cfg.get("database_file")
    if db_file:
        with open(db_file) as fh:
            entries = json.load(fh) + entries
    db = _GeoDb(entries)

    def fn(doc):
        ip = doc.get(field)
        if ip is None:
            if ignore_missing:
                return
            raise IngestProcessorException(
                f"field [{field}] not present")
        data = db.lookup(str(ip))
        if data is None:
            return                       # address not in the database
        if properties:
            data = {k: v for k, v in data.items() if k in properties}
        doc.set(target, data)
    return fn


# ---------------------------------------------------------------------------
# user agent
# ---------------------------------------------------------------------------

_UA_PATTERNS = [
    # (name, regex with version group)
    ("Edge", r"Edge?/(\d+[\w.]*)"),
    ("Opera", r"(?:Opera|OPR)/(\d+[\w.]*)"),
    ("Chrome Mobile", r"Chrome/(\d+[\w.]*) Mobile"),
    ("Chrome", r"Chrome/(\d+[\w.]*)"),
    ("Firefox", r"Firefox/(\d+[\w.]*)"),
    ("MSIE", r"MSIE (\d+[\w.]*)"),
    ("IE", r"Trident/.*rv:(\d+[\w.]*)"),
    ("Mobile Safari", r"Version/(\d+[\w.]*).*Mobile.*Safari"),
    ("Safari", r"Version/(\d+[\w.]*).*Safari"),
    ("curl", r"curl/(\d+[\w.]*)"),
    ("wget", r"[Ww]get/(\d+[\w.]*)"),
    ("Googlebot", r"Googlebot/(\d+[\w.]*)"),
    ("bingbot", r"bingbot/(\d+[\w.]*)"),
]

_OS_PATTERNS = [
    ("Windows", r"Windows NT (\d+[\d.]*)"),
    ("Android", r"Android (\d+[\w.]*)"),
    ("iOS", r"iPhone OS (\d+[_\w]*)"),
    ("iOS", r"CPU OS (\d+[_\w]*)"),
    ("Mac OS X", r"Mac OS X (\d+[_\w.]*)"),
    ("Linux", r"Linux"),
    ("Chrome OS", r"CrOS"),
]


def parse_user_agent(ua: str) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": "Other", "device": {"name": "Other"}}
    for name, pat in _UA_PATTERNS:
        m = re.search(pat, ua)
        if m:
            out["name"] = name
            out["version"] = m.group(1)
            parts = m.group(1).replace("_", ".").split(".")
            out["major"] = parts[0]
            if len(parts) > 1:
                out["minor"] = parts[1]
            break
    for os_name, pat in _OS_PATTERNS:
        m = re.search(pat, ua)
        if m:
            version = (m.group(1).replace("_", ".")
                       if m.groups() else None)
            out["os"] = {"name": os_name}
            if version:
                out["os"]["version"] = version
                out["os"]["full"] = f"{os_name} {version}"
            break
    if "Mobile" in ua or "iPhone" in ua or "Android" in ua:
        out["device"] = {"name": ("iPhone" if "iPhone" in ua
                                  else "Generic Smartphone")}
    if any(b in out["name"] for b in ("bot", "Googlebot", "bingbot")):
        out["device"] = {"name": "Spider"}
    return out


@processor("user_agent")
def _user_agent(cfg, svc):
    field = cfg["field"]
    target = cfg.get("target_field", "user_agent")
    ignore_missing = bool(cfg.get("ignore_missing", False))
    properties = cfg.get("properties")

    def fn(doc):
        ua = doc.get(field)
        if ua is None:
            if ignore_missing:
                return
            raise IngestProcessorException(
                f"field [{field}] not present")
        data = parse_user_agent(str(ua))
        if properties:
            data = {k: v for k, v in data.items() if k in properties}
        doc.set(target, data)
    return fn
