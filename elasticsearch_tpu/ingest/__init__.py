from elasticsearch_tpu.ingest.service import (
    IngestDocument,
    IngestService,
    Pipeline,
)

__all__ = ["IngestDocument", "IngestService", "Pipeline"]
