"""`attachment` ingest processor (ref: plugins/ingest-attachment —
Tika-backed content extraction). The Tika stack is replaced by a
stdlib extractor covering the text-bearing formats that need no binary
codec: plain text (charset-sniffed: BOM/UTF-16/UTF-8/latin-1), HTML
(tag-stripped, title extracted), RTF (control-word stripped), CSV, and
JSON. True binary formats (PDF/DOCX/...) are detected and reported as
unsupported rather than silently mangled — the processor contract
(field/target_field/properties/indexed_chars/ignore_missing) matches
the reference.
"""

from __future__ import annotations

import base64
import binascii
import csv
import io
import json
import re
from html.parser import HTMLParser
from typing import Any, Dict, Optional, Tuple


class _HtmlText(HTMLParser):
    _SKIP = {"script", "style"}

    def __init__(self):
        super().__init__()
        self.chunks = []
        self.title_chunks = []
        self._skip_depth = 0
        self._in_title = False

    def handle_starttag(self, tag, attrs):
        if tag in self._SKIP:
            self._skip_depth += 1
        if tag == "title":
            self._in_title = True

    def handle_endtag(self, tag):
        if tag in self._SKIP and self._skip_depth:
            self._skip_depth -= 1
        if tag == "title":
            self._in_title = False

    def handle_data(self, data):
        if self._in_title:
            self.title_chunks.append(data)
        elif not self._skip_depth:
            self.chunks.append(data)


def _decode_text(raw: bytes) -> str:
    if raw.startswith(b"\xef\xbb\xbf"):
        return raw[3:].decode("utf-8", "replace")
    if raw.startswith((b"\xff\xfe", b"\xfe\xff")):
        return raw.decode("utf-16", "replace")
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError:
        return raw.decode("latin-1", "replace")


def _strip_rtf(text: str) -> str:
    text = re.sub(r"\\'[0-9a-fA-F]{2}",
                  lambda m: bytes.fromhex(m.group(0)[2:]).decode(
                      "latin-1"), text)
    text = re.sub(r"\\[a-zA-Z]+-?\d* ?", " ", text)
    text = text.replace("{", " ").replace("}", " ").replace("\\", " ")
    return re.sub(r"\s+", " ", text).strip()


def _pdf_text(raw: bytes) -> Optional[str]:
    """Text from PDF content streams (ref: the reference parses PDFs
    through Tika/PDFBox — AttachmentProcessor.java; here a native
    reader covers the text operators): every stream object is
    inflated when FlateDecode'd, then Tj/TJ/' show-text operators are
    read, with octal escapes and hex strings decoded. Covers
    uncompressed and Flate text streams (the overwhelmingly common
    encodings); exotic filters (LZW, JBIG2, CID-keyed fonts with
    custom CMaps) fall back to detected-not-parsed."""
    import zlib
    chunks: list = []
    for m in re.finditer(rb"stream\r?\n(.*?)\r?\nendstream", raw,
                         re.DOTALL):
        data = m.group(1)
        if data[:2] in (b"\x78\x9c", b"\x78\x01", b"\x78\xda"):
            try:
                data = zlib.decompress(data)
            except zlib.error:
                continue
        if b"Tj" not in data and b"TJ" not in data \
                and b"'" not in data:
            continue
        for sm in re.finditer(
                rb"\(((?:[^()\\]|\\.)*)\)\s*(?:Tj|')"
                rb"|\[((?:[^\[\]\\]|\\.|\([^)]*\))*)\]\s*TJ"
                rb"|<([0-9A-Fa-f\s]+)>\s*Tj", data):
            if sm.group(1) is not None:
                chunks.append(_pdf_unescape(sm.group(1)))
            elif sm.group(2) is not None:
                for lit in re.finditer(rb"\(((?:[^()\\]|\\.)*)\)",
                                       sm.group(2)):
                    chunks.append(_pdf_unescape(lit.group(1)))
            else:
                hx = re.sub(rb"\s", b"", sm.group(3))
                try:
                    chunks.append(bytes.fromhex(hx.decode()).decode(
                        "latin-1"))
                except ValueError:
                    pass
        if chunks and chunks[-1] and not chunks[-1].endswith(" "):
            chunks.append(" ")
    text = re.sub(r"\s+", " ", "".join(chunks)).strip()
    return text or None


def _pdf_unescape(b: bytes) -> str:
    out = []
    i = 0
    while i < len(b):
        c = b[i]
        if c == 0x5C and i + 1 < len(b):       # backslash
            n = b[i + 1]
            esc = {0x6E: "\n", 0x72: "\r", 0x74: "\t", 0x62: "\b",
                   0x66: "\f", 0x28: "(", 0x29: ")", 0x5C: "\\"}
            if n in esc:
                out.append(esc[n])
                i += 2
                continue
            if 0x30 <= n <= 0x37:              # octal
                j = i + 1
                oct_s = ""
                while j < len(b) and len(oct_s) < 3 \
                        and 0x30 <= b[j] <= 0x37:
                    oct_s += chr(b[j])
                    j += 1
                out.append(chr(int(oct_s, 8) & 0xFF))
                i = j
                continue
            i += 1
            continue
        out.append(chr(c))
        i += 1
    return "".join(out)


def _ooxml_text(raw: bytes) -> Tuple[Optional[str], Optional[str],
                                     Optional[str]]:
    """(text, title, content_type) from an OOXML zip (docx/xlsx/pptx —
    stdlib zipfile + XML; the reference goes through Tika's OOXML
    parser). Text nodes: w:t (Word), t in sharedStrings (Excel), a:t
    (PowerPoint)."""
    import zipfile
    from xml.etree import ElementTree as ET
    try:
        zf = zipfile.ZipFile(io.BytesIO(raw))
        names = set(zf.namelist())
    except zipfile.BadZipFile:
        return None, None, None

    def texts(data, tag):
        try:
            root = ET.fromstring(data)
        except ET.ParseError:
            return []
        return [el.text for el in root.iter()
                if el.tag.endswith(tag) and el.text]

    title = None
    if "docProps/core.xml" in names:
        for t in texts(zf.read("docProps/core.xml"), "}title"):
            title = t
            break
    parts: list = []
    ctype = None
    if "word/document.xml" in names:
        ctype = ("application/vnd.openxmlformats-officedocument."
                 "wordprocessingml.document")
        parts += texts(zf.read("word/document.xml"), "}t")
    elif any(n.startswith("ppt/slides/slide") for n in names):
        ctype = ("application/vnd.openxmlformats-officedocument."
                 "presentationml.presentation")
        for n in sorted(names):
            if n.startswith("ppt/slides/slide") and n.endswith(".xml"):
                parts += texts(zf.read(n), "}t")
    elif any(n.startswith("xl/") for n in names):
        ctype = ("application/vnd.openxmlformats-officedocument."
                 "spreadsheetml.sheet")
        if "xl/sharedStrings.xml" in names:
            parts += texts(zf.read("xl/sharedStrings.xml"), "}t")
    if ctype is None:
        return None, title, None
    text = re.sub(r"\s+", " ", " ".join(parts)).strip()
    return (text or None), title, ctype


def detect_and_extract(raw: bytes) -> Tuple[str, Optional[str],
                                            Optional[str]]:
    """(content_type, extracted text | None, title | None)."""
    head = raw[:512]
    if head.startswith(b"%PDF"):
        return "application/pdf", _pdf_text(raw), None
    if head.startswith(b"PK\x03\x04"):
        text, title, ctype = _ooxml_text(raw)
        return (ctype or "application/vnd.openxmlformats-officedocument",
                text, title)
    if head.startswith(b"\xd0\xcf\x11\xe0"):
        return "application/msword", None, None
    text = _decode_text(raw)
    probe = text.lstrip()[:256].lower()
    if probe.startswith("{\\rtf"):
        return "application/rtf", _strip_rtf(text), None
    if "<html" in probe or "<!doctype html" in probe or "<body" in probe:
        p = _HtmlText()
        p.feed(text)
        body = re.sub(r"\s+", " ", " ".join(p.chunks)).strip()
        title = " ".join(p.title_chunks).strip() or None
        return "text/html", body, title
    if probe.startswith(("{", "[")):
        try:
            doc = json.loads(text)
            strings = []

            def walk(v):
                if isinstance(v, str):
                    strings.append(v)
                elif isinstance(v, dict):
                    for x in v.values():
                        walk(x)
                elif isinstance(v, list):
                    for x in v:
                        walk(x)

            walk(doc)
            return "application/json", " ".join(strings), None
        except ValueError:
            pass
    if "," in probe and "\n" in text[:2048]:
        try:
            rows = list(csv.reader(io.StringIO(text[:65536])))
            if len(rows) >= 2 and len(rows[0]) >= 2 \
                    and len({len(r) for r in rows[:10] if r}) == 1:
                return "text/csv", re.sub(r"\s+", " ", text).strip(), None
        except csv.Error:
            pass
    return "text/plain", text.strip(), None


from elasticsearch_tpu.ingest.service import processor


@processor("attachment")
def attachment_factory(cfg: Dict[str, Any], svc):
    """Factory for the `attachment` processor (ref:
    AttachmentProcessor.java — field, target_field, indexed_chars,
    properties, ignore_missing, remove_binary)."""
    field = cfg["field"]
    target = cfg.get("target_field", "attachment")
    indexed_chars = int(cfg.get("indexed_chars", 100_000))
    props = cfg.get("properties")
    ignore_missing = bool(cfg.get("ignore_missing", False))
    remove_binary = bool(cfg.get("remove_binary", False))

    def run(doc):
        b64 = doc.source.get(field)
        if b64 is None:
            if ignore_missing:
                return doc
            raise ValueError(f"field [{field}] not present as part of "
                             f"path [{field}]")
        try:
            raw = base64.b64decode(b64, validate=True)
        except (binascii.Error, ValueError):
            # the reference accepts raw bytes strings too
            raw = str(b64).encode("utf-8", "replace")
        ctype, content, title = detect_and_extract(raw)
        att: Dict[str, Any] = {"content_type": ctype,
                               "content_length": len(raw)}
        if content is not None:
            if indexed_chars >= 0:
                content = content[:indexed_chars]
            att["content"] = content
        else:
            att["content"] = ""
            att["_extraction"] = (
                f"unsupported binary format [{ctype}] — text extraction "
                f"for this type needs the full Tika-class stack")
        if title:
            att["title"] = title
        if props:
            att = {k: v for k, v in att.items() if k in set(props)}
        doc.source[target] = att
        if remove_binary:
            doc.source.pop(field, None)
        return doc

    return run
