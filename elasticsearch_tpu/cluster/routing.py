"""Operation routing + adaptive replica selection.

Ref: cluster/routing/OperationRouting.java:42 — doc routed to shard by
hash(_routing) % num_shards; for reads, one copy of each shard is chosen,
ranked by **adaptive replica selection** (EWMA response time + queue
depth from ResponseCollectorService, ref: node/ResponseCollectorService
.java:44,82).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import IndexNotFoundException
from elasticsearch_tpu.cluster.state import (
    SHARD_INITIALIZING,
    ClusterState,
    IndexShardRoutingTable,
    ShardRouting,
)
from elasticsearch_tpu.index.service import murmur3_hash


class ResponseCollectorService:
    """Per-node EWMA of service time / response time / queue size,
    reported by data nodes with each search response (ref:
    ResponseCollectorService.ComputedNodeStats)."""

    ALPHA = 0.3

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = {}

    def add_node_statistics(self, node_id: str, queue_size: int,
                            response_time_ns: float,
                            service_time_ns: float) -> None:
        with self._lock:
            st = self._stats.setdefault(node_id, {
                "queue": float(queue_size),
                "response": float(response_time_ns),
                "service": float(service_time_ns)})
            a = self.ALPHA
            st["queue"] = a * queue_size + (1 - a) * st["queue"]
            st["response"] = a * response_time_ns + (1 - a) * st["response"]
            st["service"] = a * service_time_ns + (1 - a) * st["service"]

    def rank(self, node_id: str, outstanding: int = 1) -> float:
        """ES's ARS formula (ref: ResponseCollectorService.rank):
        R(s) = response + (q_hat^3) * service, q_hat scaled by
        outstanding requests. Lower is better; unknown nodes rank 0 so
        they get tried."""
        with self._lock:
            st = self._stats.get(node_id)
            if st is None:
                return 0.0
            q_hat = st["queue"] + outstanding
            return st["response"] + (q_hat ** 3) * st["service"]


@dataclass(frozen=True)
class ShardId:
    index: str
    shard: int

    def __str__(self) -> str:
        return f"[{self.index}][{self.shard}]"


class ShardIterator:
    """An ordered walk over the copies of ONE shard group (ref:
    cluster/routing/ShardIterator / PlainShardIterator): the coordinator
    takes the first copy, and on failure asks for the next one —
    replica failover is `next_or_none()` until the group is exhausted.
    Copies arrive ARS-ranked (best first)."""

    __slots__ = ("shard_id", "_copies", "_pos")

    def __init__(self, shard_id: ShardId, copies: List[ShardRouting]):
        self.shard_id = shard_id
        self._copies = list(copies)
        self._pos = 0

    def next_or_none(self) -> Optional[ShardRouting]:
        if self._pos >= len(self._copies):
            return None
        copy = self._copies[self._pos]
        self._pos += 1
        return copy

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"ShardIterator({self.shard_id}, "
                f"{self._pos}/{len(self._copies)})")


class OperationRouting:
    """Ref: OperationRouting.java."""

    def __init__(self,
                 collector: Optional[ResponseCollectorService] = None):
        self.collector = collector or ResponseCollectorService()

    @staticmethod
    def shard_id(num_shards: int, doc_id: str,
                 routing: Optional[str] = None) -> int:
        key = routing if routing is not None else doc_id
        return abs(murmur3_hash(key)) % num_shards

    def index_shard(self, state: ClusterState, index: str, doc_id: str,
                    routing: Optional[str] = None) -> ShardId:
        imd = state.metadata.index(index)
        if imd is None:
            raise IndexNotFoundException(index)
        return ShardId(index,
                       self.shard_id(imd.number_of_shards, doc_id, routing))

    def primary_shard(self, state: ClusterState,
                      shard_id: ShardId) -> Optional[ShardRouting]:
        irt = state.routing_table.index(shard_id.index)
        if irt is None:
            return None
        table = irt.shard(shard_id.shard)
        if table is None:
            return None
        primary = table.primary
        if primary is not None and primary.active:
            return primary
        return None

    def shard_iterators(self, state: ClusterState, index: str,
                        preference: Optional[str] = None
                        ) -> List[ShardIterator]:
        """One iterator per shard group with ALL active copies ARS-ranked
        best-first, then any INITIALIZING copies as last-resort failover
        picks (ref: IndexShardRoutingTable.activeInitializingShardsRankedIt).
        The initializing tail is what survives the relocation-flip race:
        a coordinator holding the pre-flip state sends to the RELOCATING
        source, the source has already handed off and removed its copy,
        and the retry walks onto the relocation target — which by
        RPC-arrival time is started. Groups with no copy at all yield an
        EMPTY iterator so the coordinator can report them failed instead
        of silently dropping the shard."""
        irt = state.routing_table.index(index)
        if irt is None:
            return []
        groups: List[ShardIterator] = []
        for shard_num in sorted(irt.shards):
            table: IndexShardRoutingTable = irt.shards[shard_num]
            active = table.active_shards()
            if preference == "_primary":
                ranked = sorted(active, key=lambda s: not s.primary)
            else:
                ranked = sorted(active, key=lambda s: (
                    self.collector.rank(s.current_node_id or ""),
                    not s.primary))
            ranked += [s for s in table.shards
                       if s.state == SHARD_INITIALIZING]
            groups.append(ShardIterator(ShardId(index, shard_num), ranked))
        return groups

    def search_shards(self, state: ClusterState, index: str,
                      preference: Optional[str] = None
                      ) -> List[ShardRouting]:
        """One active copy per shard group, ARS-ranked (the first pick of
        each shard iterator; groups with no active copy are skipped)."""
        chosen: List[ShardRouting] = []
        for it in self.shard_iterators(state, index, preference):
            pick = it.next_or_none()
            if pick is not None:
                chosen.append(pick)
        return chosen

    def all_search_groups(self, state: ClusterState,
                          index: str) -> List[IndexShardRoutingTable]:
        irt = state.routing_table.index(index)
        return [irt.shards[k] for k in sorted(irt.shards)] if irt else []
