"""Shard allocation: deciders + balanced allocator + reroute.

Ref: cluster/routing/allocation/ — `AllocationService.reroute` computes
shard placement each time the cluster changes: pluggable
`AllocationDecider`s veto placements (same-shard, filters, throttling,
disk thresholds, retry limits; ref: decider/ package has 19), then
`BalancedShardsAllocator` picks the least-loaded allowed node by a
weight function. Shard lifecycle round-trips (`ShardStateAction`:
started/failed) feed back in here.

Pure functions over the immutable ClusterState — the master submits the
result through the coordinator's publication path.
"""

from __future__ import annotations

import uuid
from dataclasses import replace
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.cluster.shutdown import (
    INDEX_DELAYED_TIMEOUT_SETTING,
    parse_time_s,
)
from elasticsearch_tpu.cluster.state import (
    SHARD_INITIALIZING,
    SHARD_RELOCATING,
    SHARD_STARTED,
    SHARD_UNASSIGNED,
    SHUTDOWN_REMOVE,
    SHUTDOWN_RESTART,
    ClusterState,
    IndexMetadata,
    IndexRoutingTable,
    IndexShardRoutingTable,
    RoutingTable,
    ShardRouting,
)
from elasticsearch_tpu.common.errors import IllegalArgumentException

DECISION_YES = "YES"
DECISION_NO = "NO"
DECISION_THROTTLE = "THROTTLE"

# the node-drain filter: a comma-separated list of node ids (or names)
# whose shards are evacuated by reroute and which no allocation or
# relocation may target (ref: cluster.routing.allocation.exclude._id,
# FilterAllocationDecider cluster-level settings)
CLUSTER_EXCLUDE_SETTING = "cluster.routing.allocation.exclude._id"


def excluded_node_tokens(state: ClusterState) -> Set[str]:
    raw = state.metadata.persistent_settings.get(CLUSTER_EXCLUDE_SETTING)
    tokens = {t.strip() for t in str(raw).split(",") if t.strip()} \
        if raw else set()
    # a registered `remove` shutdown drains exactly like the exclude
    # filter (ref: NodeShutdownAllocationDecider — nothing may be
    # allocated to a node being removed, reroute evacuates it)
    for node_id, marker in state.metadata.node_shutdowns.items():
        if marker.type == SHUTDOWN_REMOVE:
            tokens.add(node_id)
    return tokens


def _node_tokens(state: ClusterState, node_id: str) -> Set[str]:
    node = state.nodes.get(node_id)
    tokens = {node_id}
    if node is not None and node.name:
        tokens.add(node.name)
    return tokens


class AllocationDecider:
    """Ref: decider/AllocationDecider.java — can_allocate(shard, node)."""

    name = "base"

    def can_allocate(self, shard: ShardRouting, node_id: str,
                     context: "RoutingAllocation") -> str:
        return DECISION_YES


class SameShardAllocationDecider(AllocationDecider):
    """No two copies of one shard on the same node (ref:
    SameShardAllocationDecider.java)."""

    name = "same_shard"

    def can_allocate(self, shard, node_id, context) -> str:
        for other in context.assigned_shards:
            if (other.index == shard.index
                    and other.shard_id == shard.shard_id
                    and other.current_node_id == node_id):
                return DECISION_NO
        return DECISION_YES


class FilterAllocationDecider(AllocationDecider):
    """index.routing.allocation.{require,include,exclude}._name (ref:
    FilterAllocationDecider.java)."""

    name = "filter"

    def can_allocate(self, shard, node_id, context) -> str:
        # cluster-level node drain: an excluded node (by id or name) may
        # receive nothing — reroute evacuates what it already holds
        excluded = excluded_node_tokens(context.state)
        if excluded and (_node_tokens(context.state, node_id) & excluded):
            return DECISION_NO
        imd = context.state.metadata.index(shard.index)
        if imd is None:
            return DECISION_YES
        settings = imd.settings or {}
        node = context.state.nodes.get(node_id)
        name = node.name if node else node_id
        exclude = settings.get("index.routing.allocation.exclude._name")
        if exclude and name in str(exclude).split(","):
            return DECISION_NO
        require = settings.get("index.routing.allocation.require._name")
        if require and name not in str(require).split(","):
            return DECISION_NO
        return DECISION_YES


class ThrottlingAllocationDecider(AllocationDecider):
    """Cap concurrent incoming recoveries per node (ref:
    ThrottlingAllocationDecider.java, default 2). Relocation targets
    are INITIALIZING entries, so in-flight relocations count against
    the same per-node budget as plain replica recoveries."""

    name = "throttling"

    def __init__(self, concurrent_recoveries: int = 2):
        self.concurrent_recoveries = concurrent_recoveries

    def can_allocate(self, shard, node_id, context) -> str:
        initializing = sum(
            1 for s in context.assigned_shards
            if s.current_node_id == node_id
            and s.state == SHARD_INITIALIZING)
        if initializing >= self.concurrent_recoveries:
            return DECISION_THROTTLE
        return DECISION_YES


class MaxRetryAllocationDecider(AllocationDecider):
    """Stop allocation loops after N failures (ref:
    MaxRetryAllocationDecider.java, default 5)."""

    name = "max_retry"

    def __init__(self, max_retries: int = 5):
        self.max_retries = max_retries

    def can_allocate(self, shard, node_id, context) -> str:
        failures = context.failure_counts.get(
            (shard.index, shard.shard_id, shard.primary), 0)
        if failures >= self.max_retries:
            return DECISION_NO
        return DECISION_YES


class DiskThresholdDecider(AllocationDecider):
    """Veto nodes above the high disk watermark (ref:
    DiskThresholdDecider.java; usage supplied by the monitor layer)."""

    name = "disk_threshold"

    def __init__(self, usage_fn: Optional[Callable[[str], float]] = None,
                 high_watermark: float = 0.90):
        self.usage_fn = usage_fn
        self.high_watermark = high_watermark

    def can_allocate(self, shard, node_id, context) -> str:
        if self.usage_fn is None:
            return DECISION_YES
        if self.usage_fn(node_id) >= self.high_watermark:
            return DECISION_NO
        return DECISION_YES


class RoutingAllocation:
    """Context handed to deciders during one reroute (ref:
    RoutingAllocation.java)."""

    def __init__(self, state: ClusterState,
                 assigned_shards: List[ShardRouting],
                 failure_counts: Dict[Tuple, int]):
        self.state = state
        self.assigned_shards = assigned_shards
        self.failure_counts = failure_counts


def default_deciders() -> List[AllocationDecider]:
    return [SameShardAllocationDecider(), FilterAllocationDecider(),
            ThrottlingAllocationDecider(), MaxRetryAllocationDecider(),
            DiskThresholdDecider()]


class AllocationService:
    """Ref: AllocationService.java — reroute + shard started/failed
    appliers. Owned by the master; results published as cluster state."""

    def __init__(self, deciders: Optional[List[AllocationDecider]] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.deciders = deciders or default_deciders()
        # scheduler clock (ESTPU-DET) driving delayed-unassigned
        # deadlines; without one, node-left is always immediate
        self.clock = clock
        # (index, shard, primary) -> consecutive failures
        self.failure_counts: Dict[Tuple, int] = {}

    # ------------------------------------------------------------ reroute

    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign unassigned shards to allowed nodes, balancing by shard
        count (ref: BalancedShardsAllocator weight function — simplified
        to total-shards + same-index-shards terms), then plan drain
        relocations off nodes excluded by
        ``cluster.routing.allocation.exclude._id``."""
        data_nodes = [n.node_id for n in state.nodes.data_nodes()]
        if not data_nodes:
            return state
        # drop assignments to nodes that left, unwinding half-finished
        # relocation pairs along the way
        live = set(n.node_id for n in state.nodes.nodes)
        now = self.clock() if self.clock is not None else None
        changed = False
        new_indices: Dict[str, Dict[int, List[ShardRouting]]] = {}
        for index, irt in state.routing_table.indices.items():
            for sid, table in irt.shards.items():
                group, ch = self._normalize_group(list(table.shards), live,
                                                  state, now)
                changed = changed or ch
                new_indices.setdefault(index, {})[sid] = group
        assigned = [s for shards in new_indices.values()
                    for group in shards.values() for s in group
                    if s.assigned]

        # primaries first (a replica can only initialize once its primary
        # is active), then replicas
        def sort_key(item):
            s = item
            return (not s.primary, s.index, s.shard_id)

        counts: Dict[str, int] = {n: 0 for n in data_nodes}
        for s in assigned:
            counts[s.current_node_id] = counts.get(s.current_node_id, 0) + 1

        # primary failover: if a group lost its primary but has an active
        # in-sync replica, PROMOTE it (ref: RoutingNodes
        # promoteActiveReplicaShardToPrimary + failPrimary — never allocate
        # a fresh empty primary while in-sync data exists elsewhere)
        for index, shards in new_indices.items():
            imd = state.metadata.index(index)
            for shard_id, group in shards.items():
                if any(s.primary and s.assigned for s in group):
                    continue
                in_sync = set(imd.in_sync_allocations.get(shard_id, [])) \
                    if imd else set()
                cand = next((i for i, s in enumerate(group)
                             if not s.primary and s.active
                             and s.allocation_id in in_sync), None)
                if cand is None:
                    continue
                old = next((i for i, s in enumerate(group)
                            if s.primary and not s.assigned), None)
                group[cand] = replace(group[cand], primary=True)
                if old is not None:
                    group[old] = replace(group[old], primary=False)
                changed = True

        ctx = RoutingAllocation(state, assigned, self.failure_counts)
        for index, shards in new_indices.items():
            imd = state.metadata.index(index)
            for shard_id, group in shards.items():
                primary_active = any(s.primary and s.active for s in group)
                in_sync = set(imd.in_sync_allocations.get(shard_id, [])) \
                    if imd else set()
                for i, s in enumerate(group):
                    if s.state != SHARD_UNASSIGNED:
                        continue
                    if s.delayed:
                        # waiting for its node to come back — reattach
                        # or timeout happens in _normalize_group, never
                        # a fresh allocation (ref: UnassignedInfo
                        # isDelayed skips the allocators)
                        continue
                    if not s.primary and not primary_active:
                        continue  # wait for the primary
                    if s.primary and in_sync:
                        # in-sync data exists (or existed) elsewhere —
                        # allocating an empty primary would silently lose
                        # acknowledged writes; stay red until a copy
                        # returns (ref: PrimaryShardAllocator only
                        # assigns primaries to nodes holding in-sync data)
                        continue
                    node = self._choose_node(s, data_nodes, counts, ctx)
                    if node is None:
                        continue
                    new = replace(s, state=SHARD_INITIALIZING,
                                  current_node_id=node,
                                  allocation_id=uuid.uuid4().hex[:16],
                                  unassigned_reason=None)
                    group[i] = new
                    ctx.assigned_shards.append(new)
                    counts[node] = counts.get(node, 0) + 1
                    changed = True

        # node drain: evacuate STARTED shards off excluded nodes by
        # planning relocation pairs (throttled by the deciders — a
        # drain proceeds a few shards at a time, ref: the exclude filter
        # + ThrottlingAllocationDecider interplay)
        excluded = excluded_node_tokens(state)
        if excluded:
            for index, shards in new_indices.items():
                for sid, group in shards.items():
                    for i, s in enumerate(list(group)):
                        if s.state != SHARD_STARTED:
                            continue
                        if not (_node_tokens(state, s.current_node_id)
                                & excluded):
                            continue
                        target = self._choose_node(s, data_nodes, counts,
                                                   ctx)
                        if target is None or \
                                target == s.current_node_id:
                            continue
                        tgt = self._start_relocation(group, i, target)
                        ctx.assigned_shards.append(tgt)
                        counts[target] = counts.get(target, 0) + 1
                        changed = True
        if not changed:
            return state
        return state.with_(routing_table=self._rebuild(
            state.routing_table, new_indices))

    # ------------------------------------------------- relocation helpers

    @staticmethod
    def _start_relocation(group: List[ShardRouting], i: int,
                          target_node: str) -> ShardRouting:
        """Flip group[i] STARTED → RELOCATING and append its
        INITIALIZING target entry. The source stays FIRST in the tuple,
        so `.primary` keeps resolving to the active relocating copy
        until the flip (ref: RoutingNodes.relocateShard — the pair of
        ShardRoutings sharing the relocation edge)."""
        src = group[i]
        group[i] = replace(src, state=SHARD_RELOCATING,
                           relocating_node_id=target_node)
        tgt = ShardRouting(
            index=src.index, shard_id=src.shard_id, primary=src.primary,
            state=SHARD_INITIALIZING, current_node_id=target_node,
            relocating_node_id=src.current_node_id,
            allocation_id=uuid.uuid4().hex[:16])
        group.append(tgt)
        return tgt

    def _normalize_group(self, group: List[ShardRouting],
                         live: Set[str],
                         state: Optional[ClusterState] = None,
                         now: Optional[float] = None
                         ) -> Tuple[List[ShardRouting], bool]:
        """Unwind relocation pairs whose nodes left, then unassign any
        other copy on a dead node. A dead relocation TARGET reverts its
        source to STARTED; a dead PRIMARY source aborts its target (the
        target was recovering from it); a dead REPLICA source simply
        disappears and its target carries on as a plain replica
        recovery from the primary.

        When the departed node is expected back — a registered
        ``restart`` shutdown marker, or the index sets
        ``index.unassigned.node_left.delayed_timeout`` — its copies go
        delayed-unassigned instead of plain unassigned: they keep their
        allocation_id and remember their node, the allocators skip
        them, and this same pass later either REATTACHES them in place
        when the node reappears inside its window (no peer copy — the
        data node recovers from its own disk) or fails them for real
        once the deadline lapses."""
        changed = False
        drop: Set[str] = set()
        override: Dict[str, ShardRouting] = {}
        targets = [t for t in group if t.is_relocation_target]
        for s in group:
            if not s.relocating:
                continue
            tgt = next((t for t in targets
                        if t.primary == s.primary
                        and t.relocating_node_id == s.current_node_id),
                       None)
            src_alive = s.current_node_id in live
            tgt_alive = tgt is not None and tgt.current_node_id in live
            if src_alive and tgt_alive:
                continue
            if not src_alive:
                if s.primary:
                    if tgt is not None and tgt.allocation_id:
                        drop.add(tgt.allocation_id)
                    override[s.allocation_id] = self._unassign_copy(
                        s, state, now)
                else:
                    drop.add(s.allocation_id)
                    if tgt is not None:
                        override[tgt.allocation_id] = replace(
                            tgt, relocating_node_id=None)
            else:
                # target gone (node left, or pair missing its half):
                # the source resumes as a plain started copy
                if tgt is not None and tgt.allocation_id:
                    drop.add(tgt.allocation_id)
                override[s.allocation_id] = replace(
                    s, state=SHARD_STARTED, relocating_node_id=None)
        out: List[ShardRouting] = []
        for s in group:
            if s.allocation_id is not None and s.allocation_id in drop:
                changed = True
                continue
            repl = override.get(s.allocation_id) \
                if s.allocation_id is not None else None
            if repl is not None:
                s = repl
                changed = True
            elif s.assigned and s.current_node_id not in live:
                s = self._unassign_copy(s, state, now)
                changed = True
            out.append(s)
        # delayed copies: reattach when the node returned, expire when
        # it missed its window
        final: List[ShardRouting] = []
        for s in out:
            if s.delayed:
                if s.delayed_node_id in live:
                    # back inside the window: re-initialize IN PLACE,
                    # keeping allocation_id + delayed_node_id so the
                    # data node recognises its own on-disk copy and
                    # recovers without a peer segment transfer
                    s = replace(s, state=SHARD_INITIALIZING,
                                current_node_id=s.delayed_node_id,
                                unassigned_reason=None,
                                delayed_until=None)
                    changed = True
                elif now is not None and s.delayed_until is not None \
                        and now >= s.delayed_until:
                    s = self._failed_copy(
                        s, "node left (delayed timeout elapsed)")
                    changed = True
            final.append(s)
        return final, changed

    def _unassign_copy(self, s: ShardRouting,
                       state: Optional[ClusterState],
                       now: Optional[float]) -> ShardRouting:
        """A copy lost its node: delayed-unassigned if the node is
        expected back, plain failed otherwise."""
        deadline = self._delay_deadline(state, s.current_node_id,
                                        s.index, now)
        if deadline is None:
            return self._failed_copy(s, "node left")
        return replace(s, state=SHARD_UNASSIGNED, current_node_id=None,
                       relocating_node_id=None,
                       unassigned_reason="node restarting (delayed)",
                       delayed_node_id=s.current_node_id,
                       delayed_until=deadline)

    @staticmethod
    def _delay_deadline(state: Optional[ClusterState], node_id: str,
                        index: str, now: Optional[float]
                        ) -> Optional[float]:
        """Scheduler-clock second until which this node's copies wait,
        or None for immediate reallocation. A `restart` shutdown marker
        grants registered_at + delay_s; otherwise the index-level
        delayed_timeout setting grants now + timeout."""
        if state is None or now is None:
            return None
        marker = state.metadata.shutdown(node_id)
        if marker is not None and marker.type == SHUTDOWN_RESTART:
            deadline = marker.registered_at + marker.delay_s
            return deadline if deadline > now else None
        imd = state.metadata.index(index)
        raw = (imd.settings or {}).get(INDEX_DELAYED_TIMEOUT_SETTING) \
            if imd is not None else None
        t = parse_time_s(raw)
        if t is not None and t > 0:
            return now + t
        return None

    def _choose_node(self, shard: ShardRouting, data_nodes: List[str],
                     counts: Dict[str, int],
                     ctx: RoutingAllocation) -> Optional[str]:
        best = None
        best_weight = None
        for node in data_nodes:
            decisions = [d.can_allocate(shard, node, ctx)
                         for d in self.deciders]
            if DECISION_NO in decisions or DECISION_THROTTLE in decisions:
                continue
            same_index = sum(1 for s in ctx.assigned_shards
                             if s.current_node_id == node
                             and s.index == shard.index)
            weight = (counts.get(node, 0), same_index, node)
            if best_weight is None or weight < best_weight:
                best, best_weight = node, weight
        return best

    @staticmethod
    def _rebuild(table: RoutingTable,
                 indices: Dict[str, Dict[int, List[ShardRouting]]]
                 ) -> RoutingTable:
        out = {}
        for index, shards in indices.items():
            out[index] = IndexRoutingTable(index, {
                sid: IndexShardRoutingTable(index, sid, tuple(group))
                for sid, group in shards.items()})
        return RoutingTable(out, table.version + 1)

    @staticmethod
    def _failed_copy(s: ShardRouting, reason: str) -> ShardRouting:
        return replace(s, state=SHARD_UNASSIGNED, current_node_id=None,
                       relocating_node_id=None, allocation_id=None,
                       unassigned_reason=reason,
                       delayed_node_id=None, delayed_until=None)

    # ------------------------------------------------- reroute commands

    def apply_reroute_commands(self, state: ClusterState,
                               commands: List[Dict[str, Any]],
                               explain: bool = False,
                               explanations: Optional[List[Dict]] = None
                               ) -> ClusterState:
        """Explicit allocation commands (ref: POST /_cluster/reroute,
        cluster/routing/allocation/command/*Command.java):
        ``move``, ``cancel``, ``allocate_replica``. With ``explain``,
        vetoed commands record their per-decider decisions instead of
        raising; valid commands mutate the routing table, which the
        caller publishes (and then re-reroutes, as the reference
        does)."""
        new_indices: Dict[str, Dict[int, List[ShardRouting]]] = {}
        for index, irt in state.routing_table.indices.items():
            for sid, table in irt.shards.items():
                new_indices.setdefault(index, {})[sid] = list(table.shards)
        assigned = [s for shards in new_indices.values()
                    for group in shards.values() for s in group
                    if s.assigned]
        ctx = RoutingAllocation(state, assigned, self.failure_counts)
        changed = False
        for cmd in commands:
            if not isinstance(cmd, dict) or len(cmd) != 1:
                raise IllegalArgumentException(
                    f"malformed reroute command {cmd!r}: expected "
                    "{\"move\"|\"cancel\"|\"allocate_replica\": {...}}")
            name, args = next(iter(cmd.items()))
            if name == "move":
                changed = self._cmd_move(state, new_indices, ctx, args,
                                         explain, explanations) or changed
            elif name == "cancel":
                changed = self._cmd_cancel(state, new_indices, args,
                                           explanations) or changed
            elif name == "allocate_replica":
                changed = self._cmd_allocate_replica(
                    state, new_indices, ctx, args, explain,
                    explanations) or changed
            else:
                raise IllegalArgumentException(
                    f"unknown reroute command [{name}]")
        if not changed:
            return state
        return state.with_(routing_table=self._rebuild(
            state.routing_table, new_indices))

    @staticmethod
    def _resolve_node(state: ClusterState, token: str) -> Optional[str]:
        for n in state.nodes.nodes:
            if token in (n.node_id, n.name):
                return n.node_id
        return None

    @staticmethod
    def _command_group(new_indices, index: str, shard: int
                       ) -> List[ShardRouting]:
        group = new_indices.get(index, {}).get(shard)
        if group is None:
            raise IllegalArgumentException(
                f"no such shard [{index}][{shard}]")
        return group

    def _explain_decisions(self, shard: ShardRouting, node_id: str,
                           ctx: "RoutingAllocation") -> List[Dict]:
        return [{"decider": d.name, "node": node_id,
                 "decision": d.can_allocate(shard, node_id, ctx)}
                for d in self.deciders]

    def _cmd_move(self, state, new_indices, ctx, args, explain,
                  explanations) -> bool:
        index, shard = args["index"], int(args["shard"])
        from_node = self._resolve_node(state, args["from_node"])
        to_node = self._resolve_node(state, args["to_node"])
        if from_node is None or to_node is None:
            raise IllegalArgumentException(
                f"move [{index}][{shard}]: unknown node in "
                f"[{args.get('from_node')}] -> [{args.get('to_node')}]")
        group = self._command_group(new_indices, index, shard)
        i = next((i for i, s in enumerate(group)
                  if s.current_node_id == from_node
                  and s.state == SHARD_STARTED), None)
        if i is None:
            raise IllegalArgumentException(
                f"move [{index}][{shard}]: no started copy on "
                f"[{args['from_node']}] (relocation already running, "
                "or the copy lives elsewhere)")
        decisions = self._explain_decisions(group[i], to_node, ctx)
        verdicts = {d["decision"] for d in decisions}
        entry = {"command": "move", "parameters": dict(args),
                 "decisions": decisions}
        if DECISION_NO in verdicts or DECISION_THROTTLE in verdicts:
            entry["accepted"] = False
            if explanations is not None:
                explanations.append(entry)
            if explain:
                return False
            raise IllegalArgumentException(
                f"move [{index}][{shard}] to [{args['to_node']}] "
                f"vetoed: {decisions}")
        tgt = self._start_relocation(group, i, to_node)
        ctx.assigned_shards.append(tgt)
        entry["accepted"] = True
        if explanations is not None:
            explanations.append(entry)
        return True

    def _cmd_cancel(self, state, new_indices, args, explanations) -> bool:
        index, shard = args["index"], int(args["shard"])
        node = self._resolve_node(state, args["node"])
        if node is None:
            raise IllegalArgumentException(
                f"cancel [{index}][{shard}]: unknown node "
                f"[{args.get('node')}]")
        group = self._command_group(new_indices, index, shard)
        entry = {"command": "cancel", "parameters": dict(args),
                 "accepted": True}
        for i, s in enumerate(group):
            if s.current_node_id != node:
                continue
            if s.is_relocation_target:
                # abort the incoming half; its source resumes
                for j, other in enumerate(group):
                    if other is not None and other.relocating \
                            and other.primary == s.primary \
                            and other.current_node_id == \
                            s.relocating_node_id:
                        group[j] = replace(other, state=SHARD_STARTED,
                                           relocating_node_id=None)
                group.pop(i)
                if explanations is not None:
                    explanations.append(entry)
                return True
            if s.relocating:
                # cancel by source: drop the target, revert the source
                for j in range(len(group) - 1, -1, -1):
                    other = group[j]
                    if other.is_relocation_target \
                            and other.primary == s.primary \
                            and other.relocating_node_id == \
                            s.current_node_id:
                        group.pop(j)
                group[group.index(s)] = replace(
                    s, state=SHARD_STARTED, relocating_node_id=None)
                if explanations is not None:
                    explanations.append(entry)
                return True
            if s.state == SHARD_INITIALIZING and not s.primary:
                group[i] = self._failed_copy(s, "cancelled by reroute")
                if explanations is not None:
                    explanations.append(entry)
                return True
            if s.primary and not bool(args.get("allow_primary")):
                raise IllegalArgumentException(
                    f"cancel [{index}][{shard}]: copy on "
                    f"[{args['node']}] is a started primary; pass "
                    "allow_primary to cancel it")
        raise IllegalArgumentException(
            f"cancel [{index}][{shard}]: no cancellable copy on "
            f"[{args['node']}]")

    def _cmd_allocate_replica(self, state, new_indices, ctx, args,
                              explain, explanations) -> bool:
        index, shard = args["index"], int(args["shard"])
        node = self._resolve_node(state, args["node"])
        if node is None:
            raise IllegalArgumentException(
                f"allocate_replica [{index}][{shard}]: unknown node "
                f"[{args.get('node')}]")
        group = self._command_group(new_indices, index, shard)
        if not any(s.primary and s.active for s in group):
            raise IllegalArgumentException(
                f"allocate_replica [{index}][{shard}]: primary is not "
                "active")
        i = next((i for i, s in enumerate(group)
                  if not s.primary and s.state == SHARD_UNASSIGNED
                  and not s.delayed), None)
        if i is None:
            raise IllegalArgumentException(
                f"allocate_replica [{index}][{shard}]: no unassigned "
                "replica copies")
        decisions = self._explain_decisions(group[i], node, ctx)
        verdicts = {d["decision"] for d in decisions}
        entry = {"command": "allocate_replica",
                 "parameters": dict(args), "decisions": decisions}
        if DECISION_NO in verdicts or DECISION_THROTTLE in verdicts:
            entry["accepted"] = False
            if explanations is not None:
                explanations.append(entry)
            if explain:
                return False
            raise IllegalArgumentException(
                f"allocate_replica [{index}][{shard}] on "
                f"[{args['node']}] vetoed: {decisions}")
        new = replace(group[i], state=SHARD_INITIALIZING,
                      current_node_id=node,
                      allocation_id=uuid.uuid4().hex[:16],
                      unassigned_reason=None)
        group[i] = new
        ctx.assigned_shards.append(new)
        entry["accepted"] = True
        if explanations is not None:
            explanations.append(entry)
        return True

    # ----------------------------------------------- lifecycle transitions

    def apply_started_shards(self, state: ClusterState,
                             started: List[Tuple[str, int, str]]
                             ) -> ClusterState:
        """(index, shard_id, allocation_id) initializing → started; adds
        the allocation id to the in-sync set (ref:
        IndexMetadataUpdater.applyChanges). A started relocation TARGET
        completes the move: the RELOCATING source entry is removed and
        its allocation id leaves the in-sync set (the target's data is
        its continuation)."""
        started_set = set(started)
        changed = False
        new_tables: Dict[str, IndexRoutingTable] = {}
        metadata = state.metadata

        def _in_sync_edit(index, sid, add=None, remove=None):
            nonlocal metadata
            imd = metadata.index(index)
            if imd is None:
                return
            ins = dict(imd.in_sync_allocations)
            cur = list(ins.get(sid, []))
            if add is not None and add not in cur:
                cur.append(add)
            if remove is not None:
                cur = [a for a in cur if a != remove]
            ins[sid] = cur
            metadata = metadata.with_index(
                replace(imd, in_sync_allocations=ins))

        for index, irt in state.routing_table.indices.items():
            new_shards = {}
            for sid, table in irt.shards.items():
                group: List[Optional[ShardRouting]] = list(table.shards)
                for i, s in enumerate(group):
                    if ((s.index, s.shard_id, s.allocation_id)
                            not in started_set
                            or s.state != SHARD_INITIALIZING):
                        continue
                    was_target = s.is_relocation_target
                    source_node = s.relocating_node_id
                    group[i] = replace(s, state=SHARD_STARTED,
                                       relocating_node_id=None,
                                       delayed_node_id=None)
                    changed = True
                    _in_sync_edit(index, sid, add=s.allocation_id)
                    if was_target:
                        for j, other in enumerate(group):
                            if j != i and other is not None \
                                    and other.relocating \
                                    and other.primary == s.primary \
                                    and other.current_node_id == \
                                    source_node:
                                _in_sync_edit(
                                    index, sid,
                                    remove=other.allocation_id)
                                group[j] = None
                                break
                group = [g for g in group if g is not None]
                new_shards[sid] = IndexShardRoutingTable(index, sid,
                                                         tuple(group))
            new_tables[index] = IndexRoutingTable(index, new_shards)
        if not changed:
            return state
        for key in list(self.failure_counts):
            if (key[0], key[1]) in {(i, s) for i, s, _a in started}:
                self.failure_counts.pop(key, None)
        return self.reroute(state.with_(
            routing_table=RoutingTable(new_tables,
                                       state.routing_table.version + 1),
            metadata=metadata))

    def apply_failed_shards(self, state: ClusterState,
                            failed: List[Tuple[str, int, str, str]]
                            ) -> ClusterState:
        """(index, shard_id, allocation_id, reason) → unassigned; removes
        from the in-sync set (mark-stale, ref:
        ReplicationOperation.failShardIfNeeded → ShardStateAction).
        Relocation halves unwind rather than unassign: a failed TARGET
        disappears and its source resumes serving; a failed RELOCATING
        source aborts a primary move (the target was copying from it)
        while a replica target survives as a plain recovery from the
        primary."""
        failed_ids = {(i, s, a) for i, s, a, _r in failed}
        reasons = {(i, s, a): r for i, s, a, r in failed}
        changed = False
        new_tables: Dict[str, IndexRoutingTable] = {}
        metadata = state.metadata

        def _mark_stale(index, sid, allocation_id):
            nonlocal metadata
            imd = metadata.index(index)
            if imd is None or not allocation_id:
                return
            ins = dict(imd.in_sync_allocations)
            ins[sid] = [a for a in ins.get(sid, []) if a != allocation_id]
            metadata = metadata.with_index(
                replace(imd, in_sync_allocations=ins))

        for index, irt in state.routing_table.indices.items():
            new_shards = {}
            for sid, table in irt.shards.items():
                group: List[Optional[ShardRouting]] = list(table.shards)
                for i, s in enumerate(group):
                    if s is None:
                        continue
                    key = (s.index, s.shard_id, s.allocation_id)
                    if key not in failed_ids or not s.assigned:
                        continue
                    self.failure_counts[
                        (s.index, s.shard_id, s.primary)] = \
                        self.failure_counts.get(
                            (s.index, s.shard_id, s.primary), 0) + 1
                    changed = True
                    if s.is_relocation_target:
                        # abort the incoming half; the source resumes
                        for j, other in enumerate(group):
                            if other is not None and other.relocating \
                                    and other.primary == s.primary \
                                    and other.current_node_id == \
                                    s.relocating_node_id:
                                group[j] = replace(
                                    other, state=SHARD_STARTED,
                                    relocating_node_id=None)
                        group[i] = None
                        continue
                    if s.relocating:
                        for j, other in enumerate(group):
                            if other is not None \
                                    and other.is_relocation_target \
                                    and other.primary == s.primary \
                                    and other.relocating_node_id == \
                                    s.current_node_id:
                                if s.primary:
                                    group[j] = None
                                else:
                                    group[j] = replace(
                                        other, relocating_node_id=None)
                        if s.primary:
                            # a failed primary's id must stay in-sync —
                            # its data still counts, and wiping it would
                            # let reroute allocate a fresh empty primary
                            # over acknowledged writes
                            group[i] = self._failed_copy(s, reasons[key])
                        else:
                            # the target is this replica's replacement:
                            # dropping the entry keeps the copy count
                            _mark_stale(index, sid, s.allocation_id)
                            group[i] = None
                        continue
                    # mark REPLICAS stale (out of the in-sync set);
                    # primaries keep their id in-sync (see above)
                    if not s.primary:
                        _mark_stale(index, sid, s.allocation_id)
                    group[i] = self._failed_copy(s, reasons[key])
                group = [g for g in group if g is not None]
                new_shards[sid] = IndexShardRoutingTable(index, sid,
                                                         tuple(group))
            new_tables[index] = IndexRoutingTable(index, new_shards)
        if not changed:
            return state
        return self.reroute(state.with_(
            routing_table=RoutingTable(new_tables,
                                       state.routing_table.version + 1),
            metadata=metadata))


def create_index_state(state: ClusterState, allocation: AllocationService,
                       name: str, number_of_shards: int = 1,
                       number_of_replicas: int = 0,
                       settings: Optional[Dict] = None,
                       mappings: Optional[Dict] = None) -> ClusterState:
    """Master-side create-index task (ref:
    MetadataCreateIndexService.applyCreateIndexRequest): add metadata +
    unassigned routing entries, then reroute."""
    if state.metadata.index(name) is not None:
        from elasticsearch_tpu.common.errors import (
            ResourceAlreadyExistsException,
        )
        raise ResourceAlreadyExistsException(
            f"index [{name}] already exists")
    imd = IndexMetadata(index=name, uuid=uuid.uuid4().hex[:20],
                        number_of_shards=number_of_shards,
                        number_of_replicas=number_of_replicas,
                        settings=settings or {}, mappings=mappings or {})
    shards = {}
    for sid in range(number_of_shards):
        group = [ShardRouting(index=name, shard_id=sid, primary=True,
                              unassigned_reason="index created")]
        for _ in range(number_of_replicas):
            group.append(ShardRouting(index=name, shard_id=sid,
                                      primary=False,
                                      unassigned_reason="index created"))
        shards[sid] = IndexShardRoutingTable(name, sid, tuple(group))
    new_state = state.with_(
        metadata=state.metadata.with_index(imd),
        routing_table=state.routing_table.with_index(
            IndexRoutingTable(name, shards)))
    return allocation.reroute(new_state)


def delete_index_state(state: ClusterState, name: str) -> ClusterState:
    if state.metadata.index(name) is None:
        from elasticsearch_tpu.common.errors import IndexNotFoundException
        raise IndexNotFoundException(name)
    return state.with_(
        metadata=state.metadata.without_index(name),
        routing_table=state.routing_table.without_index(name))
