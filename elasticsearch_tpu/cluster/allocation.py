"""Shard allocation: deciders + balanced allocator + reroute.

Ref: cluster/routing/allocation/ — `AllocationService.reroute` computes
shard placement each time the cluster changes: pluggable
`AllocationDecider`s veto placements (same-shard, filters, throttling,
disk thresholds, retry limits; ref: decider/ package has 19), then
`BalancedShardsAllocator` picks the least-loaded allowed node by a
weight function. Shard lifecycle round-trips (`ShardStateAction`:
started/failed) feed back in here.

Pure functions over the immutable ClusterState — the master submits the
result through the coordinator's publication path.
"""

from __future__ import annotations

import uuid
from dataclasses import replace
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.state import (
    SHARD_INITIALIZING,
    SHARD_STARTED,
    SHARD_UNASSIGNED,
    ClusterState,
    IndexMetadata,
    IndexRoutingTable,
    IndexShardRoutingTable,
    RoutingTable,
    ShardRouting,
)

DECISION_YES = "YES"
DECISION_NO = "NO"
DECISION_THROTTLE = "THROTTLE"


class AllocationDecider:
    """Ref: decider/AllocationDecider.java — can_allocate(shard, node)."""

    name = "base"

    def can_allocate(self, shard: ShardRouting, node_id: str,
                     context: "RoutingAllocation") -> str:
        return DECISION_YES


class SameShardAllocationDecider(AllocationDecider):
    """No two copies of one shard on the same node (ref:
    SameShardAllocationDecider.java)."""

    name = "same_shard"

    def can_allocate(self, shard, node_id, context) -> str:
        for other in context.assigned_shards:
            if (other.index == shard.index
                    and other.shard_id == shard.shard_id
                    and other.current_node_id == node_id):
                return DECISION_NO
        return DECISION_YES


class FilterAllocationDecider(AllocationDecider):
    """index.routing.allocation.{require,include,exclude}._name (ref:
    FilterAllocationDecider.java)."""

    name = "filter"

    def can_allocate(self, shard, node_id, context) -> str:
        imd = context.state.metadata.index(shard.index)
        if imd is None:
            return DECISION_YES
        settings = imd.settings or {}
        node = context.state.nodes.get(node_id)
        name = node.name if node else node_id
        exclude = settings.get("index.routing.allocation.exclude._name")
        if exclude and name in str(exclude).split(","):
            return DECISION_NO
        require = settings.get("index.routing.allocation.require._name")
        if require and name not in str(require).split(","):
            return DECISION_NO
        return DECISION_YES


class ThrottlingAllocationDecider(AllocationDecider):
    """Cap concurrent incoming recoveries per node (ref:
    ThrottlingAllocationDecider.java, default 2)."""

    name = "throttling"

    def __init__(self, concurrent_recoveries: int = 2):
        self.concurrent_recoveries = concurrent_recoveries

    def can_allocate(self, shard, node_id, context) -> str:
        initializing = sum(
            1 for s in context.assigned_shards
            if s.current_node_id == node_id
            and s.state == SHARD_INITIALIZING)
        if initializing >= self.concurrent_recoveries:
            return DECISION_THROTTLE
        return DECISION_YES


class MaxRetryAllocationDecider(AllocationDecider):
    """Stop allocation loops after N failures (ref:
    MaxRetryAllocationDecider.java, default 5)."""

    name = "max_retry"

    def __init__(self, max_retries: int = 5):
        self.max_retries = max_retries

    def can_allocate(self, shard, node_id, context) -> str:
        failures = context.failure_counts.get(
            (shard.index, shard.shard_id, shard.primary), 0)
        if failures >= self.max_retries:
            return DECISION_NO
        return DECISION_YES


class DiskThresholdDecider(AllocationDecider):
    """Veto nodes above the high disk watermark (ref:
    DiskThresholdDecider.java; usage supplied by the monitor layer)."""

    name = "disk_threshold"

    def __init__(self, usage_fn: Optional[Callable[[str], float]] = None,
                 high_watermark: float = 0.90):
        self.usage_fn = usage_fn
        self.high_watermark = high_watermark

    def can_allocate(self, shard, node_id, context) -> str:
        if self.usage_fn is None:
            return DECISION_YES
        if self.usage_fn(node_id) >= self.high_watermark:
            return DECISION_NO
        return DECISION_YES


class RoutingAllocation:
    """Context handed to deciders during one reroute (ref:
    RoutingAllocation.java)."""

    def __init__(self, state: ClusterState,
                 assigned_shards: List[ShardRouting],
                 failure_counts: Dict[Tuple, int]):
        self.state = state
        self.assigned_shards = assigned_shards
        self.failure_counts = failure_counts


def default_deciders() -> List[AllocationDecider]:
    return [SameShardAllocationDecider(), FilterAllocationDecider(),
            ThrottlingAllocationDecider(), MaxRetryAllocationDecider(),
            DiskThresholdDecider()]


class AllocationService:
    """Ref: AllocationService.java — reroute + shard started/failed
    appliers. Owned by the master; results published as cluster state."""

    def __init__(self, deciders: Optional[List[AllocationDecider]] = None):
        self.deciders = deciders or default_deciders()
        # (index, shard, primary) -> consecutive failures
        self.failure_counts: Dict[Tuple, int] = {}

    # ------------------------------------------------------------ reroute

    def reroute(self, state: ClusterState) -> ClusterState:
        """Assign unassigned shards to allowed nodes, balancing by shard
        count (ref: BalancedShardsAllocator weight function — simplified
        to total-shards + same-index-shards terms)."""
        data_nodes = [n.node_id for n in state.nodes.data_nodes()]
        if not data_nodes:
            return state
        all_shards = state.routing_table.all_shards()
        assigned = [s for s in all_shards if s.assigned]
        # drop assignments to nodes that left
        live = set(n.node_id for n in state.nodes.nodes)
        changed = False
        new_indices: Dict[str, Dict[int, List[ShardRouting]]] = {}
        for s in all_shards:
            if s.assigned and s.current_node_id not in live:
                s = self._failed_copy(s, "node left")
                changed = True
            new_indices.setdefault(s.index, {}).setdefault(
                s.shard_id, []).append(s)
        assigned = [s for shards in new_indices.values()
                    for group in shards.values() for s in group
                    if s.assigned]

        # primaries first (a replica can only initialize once its primary
        # is active), then replicas
        def sort_key(item):
            s = item
            return (not s.primary, s.index, s.shard_id)

        counts: Dict[str, int] = {n: 0 for n in data_nodes}
        for s in assigned:
            counts[s.current_node_id] = counts.get(s.current_node_id, 0) + 1

        # primary failover: if a group lost its primary but has an active
        # in-sync replica, PROMOTE it (ref: RoutingNodes
        # promoteActiveReplicaShardToPrimary + failPrimary — never allocate
        # a fresh empty primary while in-sync data exists elsewhere)
        for index, shards in new_indices.items():
            imd = state.metadata.index(index)
            for shard_id, group in shards.items():
                if any(s.primary and s.assigned for s in group):
                    continue
                in_sync = set(imd.in_sync_allocations.get(shard_id, [])) \
                    if imd else set()
                cand = next((i for i, s in enumerate(group)
                             if not s.primary and s.active
                             and s.allocation_id in in_sync), None)
                if cand is None:
                    continue
                old = next((i for i, s in enumerate(group)
                            if s.primary and not s.assigned), None)
                group[cand] = replace(group[cand], primary=True)
                if old is not None:
                    group[old] = replace(group[old], primary=False)
                changed = True

        ctx = RoutingAllocation(state, assigned, self.failure_counts)
        for index, shards in new_indices.items():
            imd = state.metadata.index(index)
            for shard_id, group in shards.items():
                primary_active = any(s.primary and s.active for s in group)
                in_sync = set(imd.in_sync_allocations.get(shard_id, [])) \
                    if imd else set()
                for i, s in enumerate(group):
                    if s.state != SHARD_UNASSIGNED:
                        continue
                    if not s.primary and not primary_active:
                        continue  # wait for the primary
                    if s.primary and in_sync:
                        # in-sync data exists (or existed) elsewhere —
                        # allocating an empty primary would silently lose
                        # acknowledged writes; stay red until a copy
                        # returns (ref: PrimaryShardAllocator only
                        # assigns primaries to nodes holding in-sync data)
                        continue
                    node = self._choose_node(s, data_nodes, counts, ctx)
                    if node is None:
                        continue
                    new = replace(s, state=SHARD_INITIALIZING,
                                  current_node_id=node,
                                  allocation_id=uuid.uuid4().hex[:16],
                                  unassigned_reason=None)
                    group[i] = new
                    ctx.assigned_shards.append(new)
                    counts[node] = counts.get(node, 0) + 1
                    changed = True
        if not changed:
            return state
        return state.with_(routing_table=self._rebuild(
            state.routing_table, new_indices))

    def _choose_node(self, shard: ShardRouting, data_nodes: List[str],
                     counts: Dict[str, int],
                     ctx: RoutingAllocation) -> Optional[str]:
        best = None
        best_weight = None
        for node in data_nodes:
            decisions = [d.can_allocate(shard, node, ctx)
                         for d in self.deciders]
            if DECISION_NO in decisions or DECISION_THROTTLE in decisions:
                continue
            same_index = sum(1 for s in ctx.assigned_shards
                             if s.current_node_id == node
                             and s.index == shard.index)
            weight = (counts.get(node, 0), same_index, node)
            if best_weight is None or weight < best_weight:
                best, best_weight = node, weight
        return best

    @staticmethod
    def _rebuild(table: RoutingTable,
                 indices: Dict[str, Dict[int, List[ShardRouting]]]
                 ) -> RoutingTable:
        out = {}
        for index, shards in indices.items():
            out[index] = IndexRoutingTable(index, {
                sid: IndexShardRoutingTable(index, sid, tuple(group))
                for sid, group in shards.items()})
        return RoutingTable(out, table.version + 1)

    @staticmethod
    def _failed_copy(s: ShardRouting, reason: str) -> ShardRouting:
        return replace(s, state=SHARD_UNASSIGNED, current_node_id=None,
                       relocating_node_id=None, allocation_id=None,
                       unassigned_reason=reason)

    # ----------------------------------------------- lifecycle transitions

    def apply_started_shards(self, state: ClusterState,
                             started: List[Tuple[str, int, str]]
                             ) -> ClusterState:
        """(index, shard_id, allocation_id) initializing → started; adds
        the allocation id to the in-sync set (ref:
        IndexMetadataUpdater.applyChanges)."""
        started_set = set(started)
        changed = False
        new_tables: Dict[str, IndexRoutingTable] = {}
        metadata = state.metadata
        for index, irt in state.routing_table.indices.items():
            new_shards = {}
            for sid, table in irt.shards.items():
                group = []
                for s in table.shards:
                    if ((s.index, s.shard_id, s.allocation_id)
                            in started_set
                            and s.state == SHARD_INITIALIZING):
                        s = replace(s, state=SHARD_STARTED)
                        changed = True
                        imd = metadata.index(index)
                        if imd is not None:
                            ins = dict(imd.in_sync_allocations)
                            cur = list(ins.get(sid, []))
                            if s.allocation_id not in cur:
                                cur.append(s.allocation_id)
                            ins[sid] = cur
                            metadata = metadata.with_index(
                                replace(imd, in_sync_allocations=ins))
                    group.append(s)
                new_shards[sid] = IndexShardRoutingTable(index, sid,
                                                         tuple(group))
            new_tables[index] = IndexRoutingTable(index, new_shards)
        if not changed:
            return state
        for key in list(self.failure_counts):
            if (key[0], key[1]) in {(i, s) for i, s, _a in started}:
                self.failure_counts.pop(key, None)
        return self.reroute(state.with_(
            routing_table=RoutingTable(new_tables,
                                       state.routing_table.version + 1),
            metadata=metadata))

    def apply_failed_shards(self, state: ClusterState,
                            failed: List[Tuple[str, int, str, str]]
                            ) -> ClusterState:
        """(index, shard_id, allocation_id, reason) → unassigned; removes
        from the in-sync set (mark-stale, ref:
        ReplicationOperation.failShardIfNeeded → ShardStateAction)."""
        failed_ids = {(i, s, a) for i, s, a, _r in failed}
        reasons = {(i, s, a): r for i, s, a, r in failed}
        changed = False
        new_tables: Dict[str, IndexRoutingTable] = {}
        metadata = state.metadata
        for index, irt in state.routing_table.indices.items():
            new_shards = {}
            for sid, table in irt.shards.items():
                group = []
                for s in table.shards:
                    key = (s.index, s.shard_id, s.allocation_id)
                    if key in failed_ids and s.assigned:
                        self.failure_counts[
                            (s.index, s.shard_id, s.primary)] = \
                            self.failure_counts.get(
                                (s.index, s.shard_id, s.primary), 0) + 1
                        # mark REPLICAS stale (out of the in-sync set);
                        # a failed primary's id must stay in-sync — its
                        # data still counts, and wiping it would let
                        # reroute allocate a fresh empty primary over
                        # acknowledged writes
                        imd = metadata.index(index)
                        if imd is not None and s.allocation_id \
                                and not s.primary:
                            ins = dict(imd.in_sync_allocations)
                            cur = [a for a in ins.get(sid, [])
                                   if a != s.allocation_id]
                            ins[sid] = cur
                            metadata = metadata.with_index(
                                replace(imd, in_sync_allocations=ins))
                        s = self._failed_copy(s, reasons[key])
                        changed = True
                    group.append(s)
                new_shards[sid] = IndexShardRoutingTable(index, sid,
                                                         tuple(group))
            new_tables[index] = IndexRoutingTable(index, new_shards)
        if not changed:
            return state
        return self.reroute(state.with_(
            routing_table=RoutingTable(new_tables,
                                       state.routing_table.version + 1),
            metadata=metadata))


def create_index_state(state: ClusterState, allocation: AllocationService,
                       name: str, number_of_shards: int = 1,
                       number_of_replicas: int = 0,
                       settings: Optional[Dict] = None,
                       mappings: Optional[Dict] = None) -> ClusterState:
    """Master-side create-index task (ref:
    MetadataCreateIndexService.applyCreateIndexRequest): add metadata +
    unassigned routing entries, then reroute."""
    if state.metadata.index(name) is not None:
        from elasticsearch_tpu.common.errors import (
            ResourceAlreadyExistsException,
        )
        raise ResourceAlreadyExistsException(
            f"index [{name}] already exists")
    imd = IndexMetadata(index=name, uuid=uuid.uuid4().hex[:20],
                        number_of_shards=number_of_shards,
                        number_of_replicas=number_of_replicas,
                        settings=settings or {}, mappings=mappings or {})
    shards = {}
    for sid in range(number_of_shards):
        group = [ShardRouting(index=name, shard_id=sid, primary=True,
                              unassigned_reason="index created")]
        for _ in range(number_of_replicas):
            group.append(ShardRouting(index=name, shard_id=sid,
                                      primary=False,
                                      unassigned_reason="index created"))
        shards[sid] = IndexShardRoutingTable(name, sid, tuple(group))
    new_state = state.with_(
        metadata=state.metadata.with_index(imd),
        routing_table=state.routing_table.with_index(
            IndexRoutingTable(name, shards)))
    return allocation.reroute(new_state)


def delete_index_state(state: ClusterState, name: str) -> ClusterState:
    if state.metadata.index(name) is None:
        from elasticsearch_tpu.common.errors import IndexNotFoundException
        raise IndexNotFoundException(name)
    return state.with_(
        metadata=state.metadata.without_index(name),
        routing_table=state.routing_table.without_index(name))
