"""Incremental, corruption-safe persisted cluster state.

The analogue of the reference's gateway metadata store (ref:
gateway/PersistedClusterStateService.java:117,172-193 — a Lucene index
holding one document per index metadata plus a global doc, updated
INCREMENTALLY so a state publish rewrites only what changed, committed
with fsync discipline, and recovered by reading the last commit).

Design here: an append-only framed log with commit barriers.

- Records are ``[u32 len][u32 crc32][payload json]``; types:
  ``full``   — complete serialized ClusterState (generation base)
  ``term``   — current term bump
  ``index``  — one index's metadata (upsert by name)
  ``rmindex``— index removal
  ``global`` — everything in the state EXCEPT per-index metadata
  ``commit`` — barrier carrying (term, version): all records since the
               previous barrier become visible atomically
- A publish appends only the CHANGED index docs + the global doc (when
  changed) + one commit, then fsyncs once — incremental like the
  reference's per-doc Lucene updates.
- Recovery replays the latest generation up to the LAST VALID commit:
  a torn tail (truncated frame, CRC mismatch, missing commit) rolls
  back to the previous barrier, so a kill -9 during publish can never
  lose a previously committed state.
- When the log exceeds ``rotate_bytes`` the store writes a new
  generation file starting from a ``full`` record, fsyncs file + dir,
  then removes older generations (the Lucene-commit + segment-merge
  analogue).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.coordination import PersistedState
from elasticsearch_tpu.cluster.state import ClusterState

_FRAME = struct.Struct(">II")


def _append_record(f, rtype: str, payload: Dict[str, Any]) -> int:
    body = json.dumps({"t": rtype, "p": payload},
                      separators=(",", ":")).encode("utf-8")
    f.write(_FRAME.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF))
    f.write(body)
    return _FRAME.size + len(body)


def _read_records(path: str):
    """Yield (rtype, payload, end_offset) for every intact record; stop
    silently at the first torn/corrupt frame (the recovery contract)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return
    off = 0
    n = len(data)
    while off + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + length
        if end > n:
            return                      # torn tail
        body = data[start:end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return                      # corrupt frame: stop replay here
        try:
            rec = json.loads(body.decode("utf-8"))
        except ValueError:
            return
        yield rec.get("t"), rec.get("p"), end
        off = end


class PersistedClusterStateStore:
    """The on-disk store. One live generation file ``meta-<gen>.log``
    under ``<dir>/_state``."""

    def __init__(self, data_path: str, rotate_bytes: int = 4 * 1024 * 1024):
        self.dir = os.path.join(data_path, "_state")
        os.makedirs(self.dir, exist_ok=True)
        self.rotate_bytes = rotate_bytes
        self._f = None
        self._size = 0
        self._gen = 0
        self._term = 0
        self._state: Optional[ClusterState] = None
        # the per-index docs as last WRITTEN (for diffing)
        self._written_indices: Dict[str, Any] = {}
        self._written_global: Optional[str] = None
        self._load()

    # ------------------------------------------------------------ loading
    def _generations(self) -> List[int]:
        gens = []
        for name in os.listdir(self.dir):
            if name.startswith("meta-") and name.endswith(".log"):
                try:
                    gens.append(int(name[5:-4]))
                except ValueError:
                    pass
        return sorted(gens)

    def _gen_path(self, gen: int) -> str:
        return os.path.join(self.dir, f"meta-{gen}.log")

    def _load(self) -> None:
        gens = self._generations()
        for gen in reversed(gens):
            ok = self._replay(self._gen_path(gen))
            if ok:
                self._gen = gen
                break
        else:
            self._gen = gens[-1] if gens else 0
            # No generation replayed to a commit (e.g. kill -9 during the
            # very first publish left a torn frame and no barrier). The
            # chosen file may still end in a corrupt tail; appending after
            # it would hide every later fsynced record — including future
            # commit barriers — behind the bad frame on the next replay.
            # Truncate to the last intact record boundary (or 0) first,
            # mirroring what _replay does on the commit path.
            path = self._gen_path(self._gen)
            if os.path.exists(path):
                valid_end = 0
                for _rt, _p, end in _read_records(path):
                    valid_end = end
                if os.path.getsize(path) > valid_end:
                    with open(path, "r+b") as f:
                        f.truncate(valid_end)
                        f.flush()
                        os.fsync(f.fileno())
        self._open_for_append()

    def _replay(self, path: str) -> bool:
        """Apply records up to the last valid commit. Returns True if at
        least one commit was seen (generation usable). The file is then
        TRUNCATED to that commit's byte offset: appending after a torn
        tail without truncating would leave every later record hidden
        behind the corrupt frame on the next replay."""
        term = 0
        state_d: Optional[Dict[str, Any]] = None
        indices: Dict[str, Any] = {}
        global_d: Optional[str] = None
        committed = None   # (term, state_d, indices, global_d) snapshot
        commit_off = 0
        for rtype, payload, end in _read_records(path):
            if rtype == "full":
                state_d = payload
                indices = dict(payload.get("metadata", {})
                               .get("indices", {}))
                global_d = None
            elif rtype == "term":
                term = int(payload["term"])
            elif rtype == "index":
                indices[payload["name"]] = payload["imd"]
            elif rtype == "rmindex":
                indices.pop(payload["name"], None)
            elif rtype == "global":
                global_d = payload["state"]
            elif rtype == "commit":
                committed = (term, state_d, dict(indices), global_d)
                commit_off = end
        if committed is None:
            return False
        if os.path.getsize(path) > commit_off:
            with open(path, "r+b") as f:
                f.truncate(commit_off)
                f.flush()
                os.fsync(f.fileno())
        term, state_d, indices, global_d = committed
        base = json.loads(global_d) if global_d is not None else state_d
        if base is None:
            return False
        base = dict(base)
        md = dict(base.get("metadata", {}))
        md["indices"] = indices
        base["metadata"] = md
        self._term = term
        self._state = ClusterState.from_dict(base)
        self._written_indices = {
            name: json.dumps(imd, sort_keys=True)
            for name, imd in indices.items()}
        self._written_global = json.dumps(
            self._strip_indices(base), sort_keys=True)
        return True

    @staticmethod
    def _strip_indices(state_d: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(state_d)
        md = dict(out.get("metadata", {}))
        md["indices"] = {}
        out["metadata"] = md
        return out

    # ------------------------------------------------------------ writing
    def _open_for_append(self) -> None:
        path = self._gen_path(self._gen)
        self._f = open(path, "ab")
        self._size = self._f.tell()

    def _fsync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def _fsync_dir(self) -> None:
        fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def current_term(self) -> int:
        return self._term

    def last_accepted_state(self) -> Optional[ClusterState]:
        return self._state

    def set_current_term(self, term: int) -> None:
        self._term = term
        self._size += _append_record(self._f, "term", {"term": term})
        self._size += _append_record(
            self._f, "commit",
            {"term": term,
             "version": self._state.version if self._state else 0})
        self._fsync()
        self._maybe_rotate()

    def set_last_accepted_state(self, state: ClusterState) -> None:
        """Incremental publish write: changed index docs + changed global
        doc + commit barrier, ONE fsync (ref: the reference updates only
        dirty metadata documents per publication)."""
        state_d = state.to_dict()
        new_indices = {
            name: json.dumps(imd, sort_keys=True)
            for name, imd in state_d.get("metadata", {})
            .get("indices", {}).items()}
        wrote = 0
        for name, doc in new_indices.items():
            if self._written_indices.get(name) != doc:
                wrote += _append_record(self._f, "index",
                                        {"name": name,
                                         "imd": json.loads(doc)})
        for name in self._written_indices:
            if name not in new_indices:
                wrote += _append_record(self._f, "rmindex", {"name": name})
        global_doc = json.dumps(self._strip_indices(state_d),
                                sort_keys=True)
        if global_doc != self._written_global:
            wrote += _append_record(self._f, "global",
                                    {"state": global_doc})
        wrote += _append_record(self._f, "commit",
                                {"term": self._term,
                                 "version": state.version})
        self._fsync()
        self._size += wrote
        self._state = state
        self._written_indices = new_indices
        self._written_global = global_doc
        self._maybe_rotate()

    # ----------------------------------------------------------- rotation
    def _maybe_rotate(self) -> None:
        if self._size < self.rotate_bytes:
            return
        new_gen = self._gen + 1
        path = self._gen_path(new_gen)
        with open(path, "wb") as f:
            if self._state is not None:
                _append_record(f, "full", self._state.to_dict())
            _append_record(f, "term", {"term": self._term})
            _append_record(f, "commit",
                           {"term": self._term,
                            "version": self._state.version
                            if self._state else 0})
            f.flush()
            os.fsync(f.fileno())
        self._fsync_dir()
        old_f, old_gen = self._f, self._gen
        self._gen = new_gen
        self._open_for_append()
        old_f.close()
        try:
            os.remove(self._gen_path(old_gen))
        except OSError:
            pass
        self._fsync_dir()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class DurablePersistedState(PersistedState):
    """Coordinator-facing PersistedState backed by the store (ref:
    GatewayMetaState wiring the Lucene-backed service under
    CoordinationState)."""

    def __init__(self, data_path: str, **kw):
        self.store = PersistedClusterStateStore(data_path, **kw)
        loaded = self.store.last_accepted_state()
        super().__init__(term=self.store.current_term(),
                         accepted=loaded if loaded is not None else None)

    def set_current_term(self, term: int) -> None:
        self.store.set_current_term(term)
        super().set_current_term(term)

    def set_last_accepted_state(self, state: ClusterState) -> None:
        self.store.set_last_accepted_state(state)
        super().set_last_accepted_state(state)

    def close(self) -> None:
        self.store.close()
