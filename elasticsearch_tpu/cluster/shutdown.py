"""Node-shutdown plane: status computation + delayed-timeout parsing.

Ref: the reference's ``x-pack shutdown`` plugin
(TransportGetShutdownStatusAction) and
``UnassignedInfo.findNextDelayedAllocation``. A registered shutdown
marker lives in cluster-state metadata
(:class:`~elasticsearch_tpu.cluster.state.NodeShutdownMetadata`);
this module derives the operator-facing view of it — is the node
ready to be bounced, how many shard copies still live on it, is the
drain making progress — shared by the master transport handlers
(``cluster/node.py``), the allocation service, and the REST / health
surfaces.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from elasticsearch_tpu.cluster.state import (
    SHUTDOWN_COMPLETE,
    SHUTDOWN_IN_PROGRESS,
    SHUTDOWN_REMOVE,
    SHUTDOWN_RESTART,
    SHUTDOWN_STALLED,
    ClusterState,
    NodeShutdownMetadata,
)

# how long a departed `restart` node may stay away before its delayed
# copies are promoted to real unassigned and re-replicated (ref: the
# reference's index.unassigned.node_left.delayed_timeout default of 1m)
DEFAULT_SHUTDOWN_DELAY_S = 60.0

# per-index override consulted when a node leaves WITHOUT a registered
# shutdown marker (ref: UnassignedInfo.INDEX_DELAYED_NODE_LEFT_TIMEOUT)
INDEX_DELAYED_TIMEOUT_SETTING = "index.unassigned.node_left.delayed_timeout"

VALID_SHUTDOWN_TYPES = (SHUTDOWN_RESTART, SHUTDOWN_REMOVE)


def parse_time_s(raw: Any) -> Optional[float]:
    """``"30s"`` / ``"500ms"`` / ``"2m"`` / ``"1h"`` / bare number →
    seconds; None / empty / unparseable → None."""
    if raw is None or raw == "":
        return None
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        return float(raw)
    text = str(raw).strip().lower()
    # "ms" before "s" and "m" — longest suffix wins
    for suffix, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0),
                         ("h", 3600.0)):
        if text.endswith(suffix):
            try:
                return float(text[:-len(suffix)]) * mult
            except ValueError:
                return None
    try:
        return float(text)
    except ValueError:
        return None


def shards_on_node(state: ClusterState, node_id: str) -> int:
    """Shard copies still living on ``node_id`` (relocation sources
    count — their data has not finished moving off)."""
    n = 0
    for irt in state.routing_table.indices.values():
        for table in irt.shards.values():
            for s in table.shards:
                if s.current_node_id == node_id:
                    n += 1
    return n


def delayed_shards_by_node(state: ClusterState) -> Dict[str, int]:
    """delayed_node_id -> number of copies waiting for that node."""
    out: Dict[str, int] = {}
    for irt in state.routing_table.indices.values():
        for table in irt.shards.values():
            for s in table.shards:
                if s.delayed:
                    out[s.delayed_node_id] = \
                        out.get(s.delayed_node_id, 0) + 1
    return out


def shutdown_status(state: ClusterState, marker: NodeShutdownMetadata,
                    stalled: bool = False) -> str:
    """Is the node safe to bounce? ``restart`` needs no drain, so it is
    COMPLETE the moment the marker lands (delayed allocation does the
    rest). ``remove`` is COMPLETE only once the drain emptied the node,
    STALLED when the watchdog says the drain stopped making progress,
    IN_PROGRESS otherwise."""
    if marker.type == SHUTDOWN_RESTART:
        return SHUTDOWN_COMPLETE
    remaining = shards_on_node(state, marker.node_id)
    if remaining == 0:
        return SHUTDOWN_COMPLETE
    return SHUTDOWN_STALLED if stalled else SHUTDOWN_IN_PROGRESS


def describe_shutdown(state: ClusterState, marker: NodeShutdownMetadata,
                      stalled: bool = False) -> Dict[str, Any]:
    """The GET /_nodes/{id}/shutdown entry for one marker."""
    status = shutdown_status(state, marker, stalled=stalled)
    return {
        "node_id": marker.node_id,
        "type": marker.type,
        "reason": marker.reason,
        "shutdown_started": marker.registered_at,
        "allocation_delay": marker.delay_s,
        "status": status,
        "shard_migration": {
            "status": status,
            "shard_migrations_remaining":
                shards_on_node(state, marker.node_id),
        },
    }
