"""Seed-hosts providers (ref: discovery/SeedHostsProvider.java).

The reference resolves seed hosts from settings
(`discovery.seed_hosts`), from a file
(`config/unicast_hosts.txt` — FileBasedSeedHostsProvider), or from
cloud plugins. The settings- and file-based providers are implemented
here; cloud providers would plug in through the same seam (a callable
returning DiscoveryNode seeds), contributed via the plugin SPI.
"""

from __future__ import annotations

import os
from typing import List, Optional

from elasticsearch_tpu.transport.transport import DiscoveryNode

UNICAST_HOSTS_FILE = "unicast_hosts.txt"


def _parse_host(line: str) -> Optional[DiscoveryNode]:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    host, _, port = line.partition(":")
    try:
        port_no = int(port) if port else 9300
    except ValueError:
        return None
    return DiscoveryNode(node_id=f"seed-{host}-{port_no}",
                        name=f"{host}:{port_no}", host=host, port=port_no)


def file_seed_hosts(config_dir: str) -> List[DiscoveryNode]:
    """FileBasedSeedHostsProvider: one `host[:port]` per line, comments
    with `#`, re-read on every resolution so edits apply without a
    restart (the reference's documented behavior)."""
    path = os.path.join(config_dir, UNICAST_HOSTS_FILE)
    if not os.path.exists(path):
        return []
    out: List[DiscoveryNode] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            node = _parse_host(line)
            if node is not None:
                out.append(node)
    return out


def settings_seed_hosts(settings) -> List[DiscoveryNode]:
    """`discovery.seed_hosts` from node settings."""
    raw = settings.get("discovery.seed_hosts") if settings else None
    if not raw:
        return []
    hosts = raw if isinstance(raw, list) else str(raw).split(",")
    out = []
    for h in hosts:
        node = _parse_host(str(h))
        if node is not None:
            out.append(node)
    return out


def resolve_seed_hosts(config_dir: Optional[str] = None,
                       settings=None) -> List[DiscoveryNode]:
    """Union of the configured providers, settings first (ref:
    SeedHostsResolver merging provider results)."""
    out: List[DiscoveryNode] = []
    seen = set()
    plugin_seeds: List[DiscoveryNode] = []
    for provider in PLUGIN_SEED_PROVIDERS.values():
        try:
            plugin_seeds.extend(provider(settings))
        except Exception:
            # a broken cloud provider never blocks the others
            continue
    for node in (settings_seed_hosts(settings)
                 + (file_seed_hosts(config_dir) if config_dir else [])
                 + plugin_seeds):
        key = (node.host, node.port)
        if key not in seen:
            seen.add(key)
            out.append(node)
    return out


# cloud seed providers contributed by plugins (ref: the DiscoveryPlugin
# getSeedHostProviders SPI — discovery-ec2 registers "ec2" here)
PLUGIN_SEED_PROVIDERS = {}


def gce_seed_hosts(settings) -> List[DiscoveryNode]:
    """GCE Compute-API seed provider (ref: plugins/discovery-gce/.../
    GceSeedHostsProvider.java — RUNNING instances in the configured
    project/zones whose tags contain every ``discovery.gce.tags`` entry
    become seeds, addressed by their primary ``networkIP``).

    The OAuth bearer token comes from the instance metadata server
    (``cloud.gce.metadata.endpoint`` — the
    ``computeMetadata/v1/.../token`` path with ``Metadata-Flavor:
    Google``, exactly what the reference's compute-engine credential
    chain does); ``discovery.gce.endpoint`` points at the Compute API
    (in tests, an in-process fixture that verifies both requests)."""
    if not settings:
        return []
    endpoint = settings.get("discovery.gce.endpoint")
    project = settings.get("cloud.gce.project_id")
    zones = str(settings.get("cloud.gce.zone", "") or "")
    if not endpoint or not project or not zones:
        return []
    import json as _json
    import urllib.request

    token = ""
    meta = settings.get("cloud.gce.metadata.endpoint")
    if meta:
        req = urllib.request.Request(
            str(meta).rstrip("/")
            + "/computeMetadata/v1/instance/service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                token = _json.loads(resp.read()).get("access_token", "")
        except (OSError, ValueError):
            return []   # no credentials: no seeds (never a crash)
    tags = {t.strip() for t in
            str(settings.get("discovery.gce.tags", "") or "").split(",")
            if t.strip()}
    port = int(settings.get("discovery.gce.port", 9300))
    out: List[DiscoveryNode] = []
    for zone in (z.strip() for z in zones.split(",") if z.strip()):
        url = (f"{str(endpoint).rstrip('/')}/projects/{project}"
               f"/zones/{zone}/instances")
        req = urllib.request.Request(
            url, headers={"Authorization": f"Bearer {token}"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = _json.loads(resp.read())
        except (OSError, ValueError):
            continue
        for inst in payload.get("items", []):
            if inst.get("status") != "RUNNING":
                continue
            inst_tags = set((inst.get("tags") or {}).get("items") or [])
            if tags and not tags.issubset(inst_tags):
                continue
            nics = inst.get("networkInterfaces") or []
            ip = (nics[0].get("networkIP") or "").strip() if nics else ""
            if ip:
                out.append(DiscoveryNode(
                    node_id=f"seed-{ip}-{port}", name=f"{ip}:{port}",
                    host=ip, port=port))
    return out


def azure_classic_seed_hosts(settings) -> List[DiscoveryNode]:
    """Azure classic (Service Management API) seed provider (ref:
    plugins/discovery-azure-classic/.../AzureSeedHostsProvider.java —
    role instances of one hosted service become seeds).

    ``GET {endpoint}/{subscription}/services/hostedservices/{service}
    ?embed-detail=true`` with the ``x-ms-version`` header the management
    API requires; ``discovery.azure.host.type`` picks ``private_ip``
    (the role instance's IpAddress) or ``public_ip`` (the Vip+PublicPort
    of the instance endpoint named ``discovery.azure.endpoint.name``,
    default ``elasticsearch``). ``discovery.azure.deployment.name`` /
    ``.slot`` narrow which deployment is eligible."""
    if not settings:
        return []
    endpoint = settings.get("discovery.azure.endpoint")
    subscription = settings.get("cloud.azure.management.subscription.id")
    service = settings.get("cloud.azure.management.cloud.service.name")
    if not endpoint or not subscription or not service:
        return []
    import urllib.request
    import xml.etree.ElementTree as ET

    url = (f"{str(endpoint).rstrip('/')}/{subscription}"
           f"/services/hostedservices/{service}?embed-detail=true")
    req = urllib.request.Request(
        url, headers={"x-ms-version": "2014-10-01"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            xml = resp.read()
    except OSError:
        return []
    try:
        root = ET.fromstring(xml)
    except ET.ParseError:
        return []
    ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") \
        else ""
    host_type = str(settings.get("discovery.azure.host.type",
                                 "private_ip")).lower()
    ep_name = str(settings.get("discovery.azure.endpoint.name",
                               "elasticsearch"))
    want_name = settings.get("discovery.azure.deployment.name")
    want_slot = str(settings.get("discovery.azure.deployment.slot",
                                 "production")).lower()
    port = int(settings.get("discovery.azure.port", 9300))
    out: List[DiscoveryNode] = []
    for dep in root.iter(f"{ns}Deployment"):
        name = (dep.findtext(f"{ns}Name") or "").strip()
        slot = (dep.findtext(f"{ns}DeploymentSlot") or "").strip()
        if want_name and name != str(want_name):
            continue
        if want_slot and slot.lower() != want_slot:
            continue
        for ri in dep.iter(f"{ns}RoleInstance"):
            if host_type == "public_ip":
                for iep in ri.iter(f"{ns}InstanceEndpoint"):
                    if (iep.findtext(f"{ns}Name") or "").strip() != ep_name:
                        continue
                    vip = (iep.findtext(f"{ns}Vip") or "").strip()
                    pport = int(iep.findtext(f"{ns}PublicPort") or port)
                    if vip:
                        out.append(DiscoveryNode(
                            node_id=f"seed-{vip}-{pport}",
                            name=f"{vip}:{pport}", host=vip, port=pport))
            else:
                ip = (ri.findtext(f"{ns}IpAddress") or "").strip()
                if ip:
                    out.append(DiscoveryNode(
                        node_id=f"seed-{ip}-{port}", name=f"{ip}:{port}",
                        host=ip, port=port))
    return out


def ec2_seed_hosts(settings) -> List[DiscoveryNode]:
    """EC2 DescribeInstances seed provider (ref: plugins/discovery-ec2/
    .../AwsEc2SeedHostsProvider.java — running instances matching the
    configured tag filters become transport seed addresses).

    Speaks the real EC2 Query API shape (Action=DescribeInstances with
    Filter.N.Name/Filter.N.Value.1 params, SigV4-signed) against
    ``discovery.ec2.endpoint`` — in production the regional AWS
    endpoint, in tests an in-process fixture that verifies the signed
    request. ``discovery.ec2.host_type`` picks private_ip (default) or
    public_ip; ``discovery.ec2.tag.<name>`` adds tag filters."""
    endpoint = settings.get("discovery.ec2.endpoint") if settings else None
    if not endpoint:
        return []
    import urllib.request
    import urllib.parse as _up
    import xml.etree.ElementTree as ET

    from elasticsearch_tpu.repositories.cloud import _sigv4_headers

    params = [("Action", "DescribeInstances"), ("Version", "2016-11-15"),
              ("Filter.1.Name", "instance-state-name"),
              ("Filter.1.Value.1", "running")]
    fi = 2
    flat = settings.as_dict() if hasattr(settings, "as_dict") else {}
    for key in sorted(k for k in flat
                      if k.startswith("discovery.ec2.tag.")):
        tag = key[len("discovery.ec2.tag."):]
        params.append((f"Filter.{fi}.Name", f"tag:{tag}"))
        params.append((f"Filter.{fi}.Value.1", str(flat[key])))
        fi += 1
    body = _up.urlencode(params).encode()
    headers = _sigv4_headers(
        "POST", endpoint, body,
        str(settings.get("discovery.ec2.access_key", "")),
        str(settings.get("discovery.ec2.secret_key", "")),
        region=str(settings.get("discovery.ec2.region", "us-east-1")),
        service="ec2")
    headers["Content-Type"] = "application/x-www-form-urlencoded"
    req = urllib.request.Request(endpoint, data=body, method="POST",
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            xml = resp.read()
    except OSError:
        return []   # unreachable endpoint: no seeds (never a crash)
    host_type = str(settings.get("discovery.ec2.host_type",
                                 "private_ip"))
    tag_name = ("privateIpAddress" if host_type == "private_ip"
                else "ipAddress")
    port = int(settings.get("discovery.ec2.port", 9300))
    out: List[DiscoveryNode] = []
    try:
        root = ET.fromstring(xml)
    except ET.ParseError:
        return []
    ns = root.tag.partition("}")[0] + "}" if root.tag.startswith("{") \
        else ""
    for item in root.iter(f"{ns}{tag_name}"):
        ip = (item.text or "").strip()
        if ip:
            out.append(DiscoveryNode(
                node_id=f"seed-{ip}-{port}", name=f"{ip}:{port}",
                host=ip, port=port))
    return out
