"""Seed-hosts providers (ref: discovery/SeedHostsProvider.java).

The reference resolves seed hosts from settings
(`discovery.seed_hosts`), from a file
(`config/unicast_hosts.txt` — FileBasedSeedHostsProvider), or from
cloud plugins. The settings- and file-based providers are implemented
here; cloud providers would plug in through the same seam (a callable
returning DiscoveryNode seeds), contributed via the plugin SPI.
"""

from __future__ import annotations

import os
from typing import List, Optional

from elasticsearch_tpu.transport.transport import DiscoveryNode

UNICAST_HOSTS_FILE = "unicast_hosts.txt"


def _parse_host(line: str) -> Optional[DiscoveryNode]:
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    host, _, port = line.partition(":")
    try:
        port_no = int(port) if port else 9300
    except ValueError:
        return None
    return DiscoveryNode(node_id=f"seed-{host}-{port_no}",
                        name=f"{host}:{port_no}", host=host, port=port_no)


def file_seed_hosts(config_dir: str) -> List[DiscoveryNode]:
    """FileBasedSeedHostsProvider: one `host[:port]` per line, comments
    with `#`, re-read on every resolution so edits apply without a
    restart (the reference's documented behavior)."""
    path = os.path.join(config_dir, UNICAST_HOSTS_FILE)
    if not os.path.exists(path):
        return []
    out: List[DiscoveryNode] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            node = _parse_host(line)
            if node is not None:
                out.append(node)
    return out


def settings_seed_hosts(settings) -> List[DiscoveryNode]:
    """`discovery.seed_hosts` from node settings."""
    raw = settings.get("discovery.seed_hosts") if settings else None
    if not raw:
        return []
    hosts = raw if isinstance(raw, list) else str(raw).split(",")
    out = []
    for h in hosts:
        node = _parse_host(str(h))
        if node is not None:
            out.append(node)
    return out


def resolve_seed_hosts(config_dir: Optional[str] = None,
                       settings=None) -> List[DiscoveryNode]:
    """Union of the configured providers, settings first (ref:
    SeedHostsResolver merging provider results)."""
    out: List[DiscoveryNode] = []
    seen = set()
    for node in (settings_seed_hosts(settings)
                 + (file_seed_hosts(config_dir) if config_dir else [])):
        key = (node.host, node.port)
        if key not in seen:
            seen.add(key)
            out.append(node)
    return out
