"""Data-node services: local shard lifecycle, replicated writes, peer
recovery.

Three reference subsystems, recast for this runtime:

- **IndicesClusterStateService** (ref: indices/cluster/
  IndicesClusterStateService.java:100,210,236,584-607): on every applied
  cluster state, create/remove/promote local shard engines to match the
  routing table, kick off recoveries, and report shard started/failed to
  the master.
- **Replication** (ref: action/support/replication/ReplicationOperation
  .java:57,148,181,228 + TransportShardBulkAction): execute on primary
  (seqno assignment), fan out concurrently to in-sync replicas with the
  global checkpoint piggybacked, mark misbehaving copies stale via the
  master.
- **Peer recovery** (ref: indices/recovery/RecoverySourceHandler
  .java:107,149,277-306): target-initiated; phase1 = segment file copy
  (the TPU segment format's immutable files), phase2 = translog ops
  replay up to the source's max seqno; finalize marks the copy in-sync.
  Files ride one RPC at test scale — the chunked `MultiChunkTransfer`
  equivalent belongs to the C++ host runtime.
"""

from __future__ import annotations

import base64
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.cluster.state import (
    SHARD_INITIALIZING,
    SHARD_STARTED,
    ClusterState,
    ShardRouting,
)
from elasticsearch_tpu.common.errors import (
    EsRejectedExecutionException,
    is_backpressure_failure,
)
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mapper import MapperService
from elasticsearch_tpu.index.pressure import (
    IndexingPressure,
    operation_size_bytes,
)
from elasticsearch_tpu.search.context import DeviceSegmentCache
from elasticsearch_tpu.index.seqno import ReplicationTracker
from elasticsearch_tpu.index.translog import TranslogOp
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.transport.transport import (
    DiscoveryNode,
    ResponseHandler,
)
from elasticsearch_tpu.utils.breaker import CircuitBreaker

# actions
SHARD_BULK_PRIMARY = "indices:data/write/bulk[s][p]"
SHARD_BULK_REPLICA = "indices:data/write/bulk[s][r]"
START_RECOVERY = "internal:index/shard/recovery/start_recovery"
FINALIZE_RECOVERY = "internal:index/shard/recovery/finalize"
SHARD_STARTED_ACTION = "internal:cluster/shard_state/started"
SHARD_FAILED_ACTION = "internal:cluster/shard_state/failed"
GLOBAL_CKP_SYNC = "internal:index/shard/global_checkpoint_sync"

# replica-write backpressure retry (ref: a replica 429 is NOT a stale
# copy — ReplicationOperation only fails genuinely broken copies; the
# primary retries rejected replica bulks with capped backoff instead)
REPLICA_RETRY_BACKOFF_BASE = 0.25
REPLICA_RETRY_BACKOFF_CAP = 5.0
REPLICA_RETRY_MAX_ATTEMPTS = 20


@dataclass
class LocalShard:
    """One shard copy hosted on this node (the IndexShard façade, ref:
    index/shard/IndexShard.java:188)."""

    index: str
    shard_id: int
    allocation_id: str
    primary: bool
    engine: Engine
    tracker: Optional[ReplicationTracker] = None  # primary only
    state: str = "recovering"      # recovering | started
    global_checkpoint: int = -1    # replica's view (piggybacked)

    @property
    def key(self) -> Tuple[str, int]:
        return (self.index, self.shard_id)


class DataNodeService:
    """Everything a data node does below the coordination layer."""

    def __init__(self, transport, scheduler, data_path: str,
                 device_cache: Optional[DeviceSegmentCache] = None,
                 breaker_service=None,
                 indexing_pressure: Optional[IndexingPressure] = None,
                 task_manager=None):
        self.transport = transport
        self.scheduler = scheduler
        self.local_node: DiscoveryNode = transport.local_node
        self.data_path = data_path
        self.device_cache = device_cache or DeviceSegmentCache()
        # node task manager: shard-bulk handlers register their work as
        # children of the remote coordinator's task (None = untracked)
        self.task_manager = task_manager
        # memory protection: the node breaker service (transport charges
        # in_flight_requests through it) + in-flight indexing bytes
        self.breaker_service = breaker_service
        self.indexing_pressure = indexing_pressure or IndexingPressure()
        if breaker_service is not None:
            self.device_cache.set_breaker(
                breaker_service.get_breaker(CircuitBreaker.HBM))
            from elasticsearch_tpu.utils.bigarrays import BigArrays
            # searchers over this cache charge host readback buffers
            # against the request breaker (search/searcher.py)
            self.device_cache.bigarrays = BigArrays(breaker_service)
        # replica copies the primary gave up retrying under sustained
        # backpressure (observability: these lag, they are not stale)
        self.replica_backpressure_gave_up = 0
        self.shards: Dict[Tuple[str, int], LocalShard] = {}
        self.applied_state: ClusterState = ClusterState()
        os.makedirs(data_path, exist_ok=True)
        for action, handler, can_trip in [
            (SHARD_BULK_PRIMARY, self._on_primary_bulk, True),
            (SHARD_BULK_REPLICA, self._on_replica_bulk, True),
            # recovery and checkpoint traffic is exempt: shedding it
            # under pressure would fail copies and make the cluster
            # sicker (ref: recovery actions register
            # canTripCircuitBreaker=false)
            (START_RECOVERY, self._on_start_recovery, False),
            (FINALIZE_RECOVERY, self._on_finalize_recovery, False),
            (GLOBAL_CKP_SYNC, self._on_global_ckp_sync, False),
        ]:
            transport.register_request_handler(action, handler,
                                               can_trip_breaker=can_trip)

    # ---------------------------------------------------- state application

    def apply_cluster_state(self, state: ClusterState) -> None:
        """Reconcile local shards with the routing table (ref:
        IndicesClusterStateService.applyClusterState)."""
        self.applied_state = state
        my_id = self.local_node.node_id
        wanted: Dict[Tuple[str, int], ShardRouting] = {}
        for s in state.routing_table.shards_on_node(my_id):
            wanted[(s.index, s.shard_id)] = s

        # remove shards no longer assigned here (or whose index is gone)
        for key in list(self.shards):
            shard = self.shards[key]
            want = wanted.get(key)
            if want is None or want.allocation_id != shard.allocation_id:
                self._remove_shard(key)

        for key, routing in wanted.items():
            local = self.shards.get(key)
            if local is None:
                if routing.state == SHARD_INITIALIZING:
                    self._create_shard(state, routing)
                # STARTED but not local: stale routing (e.g. we restarted)
                # → master will fail it via allocation on node-left
                continue
            # promotion: replica → primary (ref: IndexShard
            # updateShardState on primary term bump)
            if routing.primary and not local.primary:
                self._promote_to_primary(state, local, routing)
            local_routing_started = routing.state == SHARD_STARTED
            if local_routing_started and local.state == "started" \
                    and local.primary:
                self._update_tracker_from_state(state, local)

    def _index_metadata(self, state: ClusterState, index: str):
        return state.metadata.index(index)

    def _shard_path(self, index: str, shard_id: int) -> str:
        imd = self.applied_state.metadata.index(index)
        uid = imd.uuid if imd else index
        return os.path.join(self.data_path, "indices", uid, str(shard_id))

    def _create_shard(self, state: ClusterState,
                      routing: ShardRouting) -> None:
        imd = state.metadata.index(routing.index)
        if imd is None:
            return
        path = self._shard_path(routing.index, routing.shard_id)
        mapper = MapperService(Settings(imd.settings), imd.mappings or None)
        engine = Engine(path, mapper)
        shard = LocalShard(routing.index, routing.shard_id,
                           routing.allocation_id, routing.primary, engine)
        self.shards[shard.key] = shard
        if routing.primary:
            # primary: recover from local store (engine ctor replayed the
            # translog) → in-sync set bootstrap → started
            shard.tracker = ReplicationTracker(
                routing.allocation_id,
                engine.tracker.checkpoint)
            shard.state = "started"
            self._send_shard_started(routing)
        else:
            # replica: peer recovery from the active primary
            self._start_peer_recovery(state, shard, routing)

    def _remove_shard(self, key: Tuple[str, int]) -> None:
        shard = self.shards.pop(key, None)
        if shard is not None:
            try:
                shard.engine.close()
            except Exception:
                pass

    def _promote_to_primary(self, state: ClusterState, shard: LocalShard,
                            routing: ShardRouting) -> None:
        """Ref: primary failover — the promoted replica bumps its primary
        term and builds a fresh ReplicationTracker from the in-sync set."""
        shard.primary = True
        shard.allocation_id = routing.allocation_id
        shard.engine.primary_term += 1
        shard.tracker = ReplicationTracker(
            routing.allocation_id, shard.engine.tracker.checkpoint)
        self._update_tracker_from_state(state, shard)

    def _update_tracker_from_state(self, state: ClusterState,
                                   shard: LocalShard) -> None:
        """Keep the primary's tracker in step with the routing table
        (ref: ReplicationTracker.updateFromMaster)."""
        if shard.tracker is None:
            return
        irt = state.routing_table.index(shard.index)
        table = irt.shard(shard.shard_id) if irt else None
        if table is None:
            return
        imd = state.metadata.index(shard.index)
        in_sync = set()
        if imd is not None:
            in_sync = set(imd.in_sync_allocations.get(shard.shard_id, []))
        for copy in table.shards:
            if copy.allocation_id and copy.allocation_id != \
                    shard.allocation_id:
                if copy.active and copy.allocation_id in in_sync:
                    shard.tracker.init_tracking(copy.allocation_id)

    # ------------------------------------------------------- shard state

    def _master_node(self) -> Optional[DiscoveryNode]:
        return self.applied_state.nodes.master_node

    def _send_shard_started(self, routing: ShardRouting) -> None:
        master = self._master_node()
        if master is None:
            # retry when a master exists
            self.scheduler.schedule(
                1.0, lambda: self._send_shard_started(routing),
                "retry-shard-started")
            return
        self.transport.send_request(
            master, SHARD_STARTED_ACTION,
            {"index": routing.index, "shard_id": routing.shard_id,
             "allocation_id": routing.allocation_id},
            ResponseHandler(lambda r: None, lambda e: None), timeout=30.0)

    def send_shard_failed(self, index: str, shard_id: int,
                          allocation_id: str, reason: str) -> None:
        master = self._master_node()
        if master is None:
            return
        self.transport.send_request(
            master, SHARD_FAILED_ACTION,
            {"index": index, "shard_id": shard_id,
             "allocation_id": allocation_id, "reason": reason},
            ResponseHandler(lambda r: None, lambda e: None), timeout=30.0)

    # ----------------------------------------------------------- writes

    def _register_child(self, action: str, description: str):
        from elasticsearch_tpu.transport.tasks import (
            register_child_of_incoming,
        )
        return register_child_of_incoming(
            self.task_manager, action, description=description)

    def execute_primary_bulk(self, index: str, shard_id: int,
                             items: List[Dict[str, Any]],
                             on_done: Callable[[List[Dict], Optional[Any]],
                                               None],
                             op_bytes: Optional[int] = None,
                             task=None) -> None:
        """Run a shard bulk on the local primary, replicate, then call
        on_done(item_results, error). ``error`` is a string for routing
        problems or an exception (typed 429 for indexing-pressure
        rejections — retryable, never partial). ``op_bytes`` is the
        coordinator's precomputed payload size (avoids re-serializing
        the bulk just to charge it); computed locally when absent."""
        shard = self.shards.get((index, shard_id))
        if shard is None or not shard.primary or shard.state != "started":
            on_done([], f"no started primary for [{index}][{shard_id}] "
                        f"on {self.local_node.name}")
            return
        # primary-stage indexing pressure: admit the whole shard bulk
        # BEFORE any engine work; the coordinator maps the typed 429
        # onto every item so the client retries the batch
        if op_bytes is None:
            op_bytes = operation_size_bytes(items)
        try:
            release = self.indexing_pressure.mark_primary_operation_started(
                op_bytes, f"[{index}][{shard_id}] bulk")
        except EsRejectedExecutionException as e:
            on_done([], e)
            return

        def done(results_, error_=None, _release=release, _cb=on_done):
            # release-on-completion: primary bytes return when the
            # operation (including replication) has fully completed
            _release()
            _cb(results_, error_)

        on_done = done
        if task is not None:
            # the current profile stage on the executing child task:
            # `_tasks?detailed=true` / hot_threads show where a long
            # bulk is (the same seam the search paths publish through)
            task.profile_stage = "bulk.primary"
        results = []
        ops_for_replicas: List[Dict[str, Any]] = []
        for item in items:
            if task is not None and task.is_cancelled():
                # cancellation poll per item batch: items not yet
                # executed report typed task_cancelled instead of
                # running (already-executed items stand — bulk items
                # are independent operations)
                results.append({
                    "id": item.get("id"),
                    "error": {"type": "task_cancelled_exception",
                              "reason": "task cancelled "
                              f"[{task.cancellation_reason()}]"},
                    "status": 400})
                continue
            try:
                if item["op"] == "index":
                    r = shard.engine.index(
                        item["id"], item["source"],
                        op_type=item.get("op_type", "index"))
                    results.append({"id": item["id"], "result": "created"
                                    if r.created else "updated",
                                    "seq_no": r.seq_no,
                                    "version": r.version, "status": 201
                                    if r.created else 200})
                    ops_for_replicas.append({
                        "op": "index", "id": item["id"],
                        "source": item["source"], "seq_no": r.seq_no,
                        "primary_term": r.primary_term})
                elif item["op"] == "delete":
                    r = shard.engine.delete(item["id"])
                    results.append({"id": item["id"],
                                    "result": "deleted" if r.found
                                    else "not_found",
                                    "seq_no": r.seq_no, "status": 200
                                    if r.found else 404})
                    ops_for_replicas.append({
                        "op": "delete", "id": item["id"],
                        "seq_no": r.seq_no,
                        "primary_term": r.primary_term})
            except Exception as e:  # noqa: BLE001 — per-item failure
                results.append({"id": item.get("id"),
                                "error": {"type": type(e).__name__,
                                          "reason": str(e)},
                                "status": 409})
        shard.tracker.update_local_checkpoint(
            shard.allocation_id, shard.engine.tracker.checkpoint)

        # fan out to active in-sync replicas (ref:
        # ReplicationOperation.performOnReplicas — concurrent, with the
        # global checkpoint piggybacked)
        replicas = self._active_replicas(index, shard_id)
        if not replicas or not ops_for_replicas:
            on_done(results, None)
            return
        if task is not None:
            task.profile_stage = "bulk.replicate"
        pending = {"n": len(replicas)}

        def one_done():
            pending["n"] -= 1
            if pending["n"] == 0:
                on_done(results, None)

        # size the replica ops ONCE; every copy's replica-stage charge
        # reuses it off the payload
        rep_bytes = operation_size_bytes(ops_for_replicas)
        for copy, node in replicas:
            payload = {
                "index": index, "shard_id": shard_id,
                "ops": ops_for_replicas,
                "op_bytes": rep_bytes,
                "global_checkpoint": shard.tracker.global_checkpoint,
                "max_seq_no": shard.engine.tracker.max_seq_no,
            }
            self._replicate_to_copy(index, shard_id, shard, copy, node,
                                    payload, one_done, task=task)

    def _replicate_to_copy(self, index: str, shard_id: int,
                           shard: LocalShard, copy: ShardRouting,
                           node: DiscoveryNode, payload: Dict[str, Any],
                           one_done: Callable[[], None],
                           attempt: int = 1, task=None) -> None:
        """One replica write, with backpressure-aware failure handling:
        a rejected (429-class) replica bulk retries the SAME copy with
        capped exponential backoff — an overloaded copy is not a stale
        copy and must never reach the master as shard-failed; any other
        failure marks the copy stale via the master as before (ref:
        ReplicationOperation.failShardIfNeeded vs. the retryable
        EsRejectedExecutionException path)."""

        def ok(resp):
            if shard.tracker is not None:
                shard.tracker.update_local_checkpoint(
                    copy.allocation_id, resp.get("local_checkpoint", -1))
            one_done()

        def fail(exc):
            if is_backpressure_failure(exc):
                if attempt < REPLICA_RETRY_MAX_ATTEMPTS:
                    backoff = min(
                        REPLICA_RETRY_BACKOFF_BASE * (2 ** (attempt - 1)),
                        REPLICA_RETRY_BACKOFF_CAP)
                    self.scheduler.schedule(
                        backoff,
                        lambda: self._replicate_to_copy(
                            index, shard_id, shard, copy, node, payload,
                            one_done, attempt + 1, task=task),
                        f"retry replica bulk [{index}][{shard_id}] "
                        f"on {node.name}")
                    return
                # sustained rejection: give up on THIS operation without
                # failing the copy — its local checkpoint simply lags
                # and seqno-based catch-up covers it once pressure
                # drains; counted for observability
                self.replica_backpressure_gave_up += 1
                import logging
                logging.getLogger(__name__).warning(
                    "[%s] replica [%s][%d] on %s still rejecting after "
                    "%d attempts; leaving it lagging (not stale)",
                    self.local_node.name, index, shard_id, node.name,
                    attempt)
                one_done()
                return
            # genuinely failed replica: mark stale via master
            self.send_shard_failed(
                index, shard_id, copy.allocation_id,
                f"replica write failed: {exc}")
            one_done()

        from contextlib import nullcontext

        from elasticsearch_tpu.telemetry import context as _telectx
        with (_telectx.activate_task(self.local_node.node_id, task)
              if task is not None else nullcontext()):
            # replica children parent to the PRIMARY's child task, so
            # `_tasks?group_by=parents` shows the full write tree
            self.transport.send_request(node, SHARD_BULK_REPLICA, payload,
                                        ResponseHandler(ok, fail),
                                        timeout=30.0)

    def _active_replicas(self, index: str, shard_id: int
                         ) -> List[Tuple[ShardRouting, DiscoveryNode]]:
        irt = self.applied_state.routing_table.index(index)
        table = irt.shard(shard_id) if irt else None
        if table is None:
            return []
        out = []
        for copy in table.shards:
            if copy.primary or not copy.active:
                continue
            node = self.applied_state.nodes.get(copy.current_node_id)
            if node is not None:
                out.append((copy, node))
        return out

    def _on_primary_bulk(self, req, channel, src) -> None:
        child = self._register_child(
            SHARD_BULK_PRIMARY,
            f"requests[{len(req.get('items', []))}], "
            f"index[{req['index']}][{req['shard_id']}]")

        def on_done(results, error):
            if child is not None:
                self.task_manager.unregister(child)
            if error:
                # exceptions keep their type on the wire (a 429-class
                # rejection must classify as retryable at the caller)
                channel.send_exception(
                    error if isinstance(error, BaseException)
                    else RuntimeError(error))
            else:
                channel.send_response({"items": results})

        self.execute_primary_bulk(req["index"], req["shard_id"],
                                  req["items"], on_done,
                                  op_bytes=req.get("op_bytes"),
                                  task=child)

    def _on_replica_bulk(self, req, channel, src) -> None:
        """Ref: TransportShardBulkAction replica path (:417) — apply ops
        with pre-assigned seqnos. Replica-stage indexing pressure admits
        the ops first (1.5x headroom — replica rejections are shed
        last); a rejection travels back typed so the primary retries
        with backoff instead of marking the copy stale."""
        # registered for observability ONLY — replica ops carry
        # pre-assigned seqnos, so skipping some mid-stream on a cancel
        # would punch seqno gaps; the whole (small) batch always applies
        child = self._register_child(
            SHARD_BULK_REPLICA,
            f"requests[{len(req.get('ops', []))}], "
            f"index[{req['index']}][{req['shard_id']}]")
        try:
            self._replica_bulk_inner(req, channel, src)
        finally:
            if child is not None:
                self.task_manager.unregister(child)

    def _replica_bulk_inner(self, req, channel, src) -> None:
        shard = self.shards.get((req["index"], req["shard_id"]))
        if shard is None:
            channel.send_exception(RuntimeError(
                f"no local copy of [{req['index']}][{req['shard_id']}]"))
            return
        rep_bytes = req.get("op_bytes")
        if rep_bytes is None:
            rep_bytes = operation_size_bytes(req["ops"])
        try:
            release = self.indexing_pressure.mark_replica_operation_started(
                rep_bytes,
                f"[{req['index']}][{req['shard_id']}] bulk[r]")
        except EsRejectedExecutionException as e:
            channel.send_exception(e)
            return
        try:
            for op in req["ops"]:
                self._apply_replica_op(shard.engine, op)
            shard.global_checkpoint = max(shard.global_checkpoint,
                                          req.get("global_checkpoint", -1))
        finally:
            # release-on-completion: replica bytes return as soon as the
            # ops are durably applied (or failed)
            release()
        channel.send_response(
            {"local_checkpoint": shard.engine.tracker.checkpoint})

    @staticmethod
    def _apply_replica_op(engine: Engine, op: Dict[str, Any]) -> None:
        if op["op"] == "index":
            engine.index(op["id"], op["source"], seq_no=op["seq_no"],
                         primary_term=op["primary_term"])
        elif op["op"] == "delete":
            engine.delete(op["id"], seq_no=op["seq_no"],
                          primary_term=op["primary_term"])

    # --------------------------------------------------------- recovery

    def _start_peer_recovery(self, state: ClusterState, shard: LocalShard,
                             routing: ShardRouting) -> None:
        irt = state.routing_table.index(routing.index)
        table = irt.shard(routing.shard_id) if irt else None
        primary = table.primary if table else None
        if primary is None or not primary.active:
            # primary not ready yet; retry on next applied state — keep a
            # timer as a safety net
            self.scheduler.schedule(
                2.0, lambda: self._retry_recovery(shard.key),
                "retry-recovery")
            return
        source_node = state.nodes.get(primary.current_node_id)
        if source_node is None:
            return

        def ok(resp):
            self._install_recovery(shard, routing, source_node, resp)

        def fail(exc):
            self.send_shard_failed(routing.index, routing.shard_id,
                                   routing.allocation_id,
                                   f"recovery failed: {exc}")

        self.transport.send_request(
            source_node, START_RECOVERY,
            {"index": routing.index, "shard_id": routing.shard_id,
             "target_allocation_id": routing.allocation_id},
            ResponseHandler(ok, fail), timeout=120.0)

    def _retry_recovery(self, key: Tuple[str, int]) -> None:
        shard = self.shards.get(key)
        if shard is None or shard.state == "started":
            return
        routing = None
        for s in self.applied_state.routing_table.shards_on_node(
                self.local_node.node_id):
            if (s.index, s.shard_id) == key and \
                    s.allocation_id == shard.allocation_id:
                routing = s
        if routing is not None and routing.state == SHARD_INITIALIZING:
            self._start_peer_recovery(self.applied_state, shard, routing)

    def _on_start_recovery(self, req, channel, src) -> None:
        """SOURCE side (ref: RecoverySourceHandler.recoverToTarget) —
        commit, snapshot files + post-commit ops, track the target."""
        shard = self.shards.get((req["index"], req["shard_id"]))
        if shard is None or not shard.primary:
            channel.send_exception(RuntimeError(
                "recovery source is not the primary"))
            return
        engine = shard.engine
        engine.flush()
        # phase1: file snapshot (commit point + segment dirs — each
        # segment is a directory of arrays.npz/stored.bin/meta.json)
        files: Dict[str, str] = {}
        commit_path = os.path.join(engine.path, "segments.json")
        for seg in engine.segments:
            seg_dir = os.path.join(engine.path, seg.name)
            if not os.path.isdir(seg_dir):
                continue
            for fname in os.listdir(seg_dir):
                with open(os.path.join(seg_dir, fname), "rb") as fh:
                    files[f"{seg.name}/{fname}"] = base64.b64encode(
                        fh.read()).decode("ascii")
        with open(commit_path, "rb") as fh:
            commit_blob = base64.b64encode(fh.read()).decode("ascii")
        # phase2: ops after the commit point
        import json as _json
        with open(commit_path) as fh:
            commit_gen = _json.load(fh)["translog_generation"]
        ops = [op.to_dict()
               for op in engine.translog.read_ops(commit_gen)]
        if shard.tracker is not None:
            shard.tracker.init_tracking(req["target_allocation_id"])
        channel.send_response({
            "files": files,
            "commit": commit_blob,
            "ops": ops,
            "max_seq_no": engine.tracker.max_seq_no,
            "global_checkpoint": (shard.tracker.global_checkpoint
                                  if shard.tracker else -1),
        })

    def _install_recovery(self, shard: LocalShard, routing: ShardRouting,
                          source_node: DiscoveryNode,
                          resp: Dict[str, Any]) -> None:
        """TARGET side: install files, replay ops, finalize."""
        path = shard.engine.path
        try:
            shard.engine.close()
        except Exception:
            pass
        for rel, blob in resp["files"].items():
            dest = os.path.join(path, rel)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as fh:
                fh.write(base64.b64decode(blob))
        with open(os.path.join(path, "segments.json"), "wb") as fh:
            fh.write(base64.b64decode(resp["commit"]))
        imd = self.applied_state.metadata.index(routing.index)
        mapper = MapperService(Settings(imd.settings if imd else {}),
                               (imd.mappings or None) if imd else None)
        engine = Engine(path, mapper)
        shard.engine = engine
        for op_d in resp["ops"]:
            self._apply_replica_op(engine, {
                "op": op_d["op_type"], "id": op_d["doc_id"],
                "source": op_d.get("source"),
                "seq_no": op_d["seq_no"],
                "primary_term": op_d["primary_term"]})
        shard.global_checkpoint = resp.get("global_checkpoint", -1)

        def ok(resp2):
            shard.state = "started"
            self._send_shard_started(routing)

        def fail(exc):
            self.send_shard_failed(routing.index, routing.shard_id,
                                   routing.allocation_id,
                                   f"finalize failed: {exc}")

        self.transport.send_request(
            source_node, FINALIZE_RECOVERY,
            {"index": routing.index, "shard_id": routing.shard_id,
             "target_allocation_id": routing.allocation_id,
             "local_checkpoint": engine.tracker.checkpoint},
            ResponseHandler(ok, fail), timeout=60.0)

    def _on_finalize_recovery(self, req, channel, src) -> None:
        shard = self.shards.get((req["index"], req["shard_id"]))
        if shard is None or shard.tracker is None:
            channel.send_exception(RuntimeError("not the primary"))
            return
        shard.tracker.mark_in_sync(req["target_allocation_id"],
                                   req["local_checkpoint"])
        channel.send_response({"ok": True})

    # ---------------------------------------------- global checkpoint sync

    def _on_global_ckp_sync(self, req, channel, src) -> None:
        shard = self.shards.get((req["index"], req["shard_id"]))
        if shard is not None:
            shard.global_checkpoint = max(shard.global_checkpoint,
                                          req.get("global_checkpoint", -1))
        channel.send_response({"ok": True})

    # ---------------------------------------------------------- lifecycle

    def refresh_all(self) -> None:
        for shard in self.shards.values():
            shard.engine.refresh()

    def close(self) -> None:
        for key in list(self.shards):
            self._remove_shard(key)
